"""Roofline cost models and absolute-utilization accounting for the hot
kernels (SURVEY.md §6: the reference publishes no performance numbers —
README.md:1-3 — so self-measured %-of-peak is the only absolute yardstick).

Each cost model counts, per sweep/step, three things XLA cannot reduce:

  * mxu_flops — matmul FLOPs (2*m*n*k per [m,k]x[k,n] contraction),
  * vpu_ops   — elementwise/compare/reduce operations (the VPU work a
                compare-reduce kernel is made of),
  * hbm_bytes — the unavoidable HBM traffic, assuming perfect fusion of
                elementwise chains (operands read once, results written
                once, broadcasts never materialized unless noted).

These are *model* counts — analytic lower bounds on the work the algorithm
specifies, not instruction counts from the compiled HLO. Utilization
(work / time / peak) computed from them is therefore conservative: real
programs pad, re-materialize, and round up to tile sizes, so the hardware is
busier than the reported fraction. That direction of error is the useful one
for "is 20.8 ms per sweep good?" questions.

Chip peaks: this image's accelerator is a TPU v5 lite (v5e) core. Public
peaks (jax-ml.github.io/scaling-book, Google Cloud docs): 197 TFLOP/s bf16
matmul, 819 GB/s HBM bandwidth. The VPU peak is NOT published; the estimate
below assumes 4 ALUs x (8x128) lanes x ~1.67 GHz ≈ 6.8e12 f32 op/s and is
marked as such. MFU is quoted against the bf16 matmul peak — the chip's
headline number — which makes MFU for VPU-dominated kernels small by
construction; vpu_frac is the honest utilization figure for those.
"""

from __future__ import annotations

import dataclasses

__all__ = [
    "ChipPeaks",
    "KernelCost",
    "CHIP_PEAKS",
    "achieved_bandwidth_gbs",
    "distribution_sweep_cost",
    "dtype_itemsize",
    "mesh2d_collective_cost",
    "vfi_sweep_cost",
    "vfi_slab_cost",
    "egm_sweep_cost",
    "egm_fused_sweep_cost",
    "ge_fused_round_cost",
    "transition_fused_round_cost",
    "panel_step_cost",
    "utilization",
]

# Interconnect peaks for the 2-D mesh collective model (public numbers,
# like the ChipPeaks): a v5e chip's ICI is 4 links x 100 GB/s-class
# bidirectional within a slice; DCN between hosts is ~25 GB/s-class per
# host NIC. Order-of-magnitude honest — the model's job is the BYTES (an
# analytic lower bound like every cost model here); the peaks only rank
# which axis a topology stresses.
ICI_BYTES_PER_SEC = 4.0e11
DCN_BYTES_PER_SEC = 2.5e10


def dtype_itemsize(dtype) -> int:
    """Bytes per element of a dtype (name, numpy/jnp dtype, or jax array
    dtype) — the dtype-aware knob every cost model's `itemsize` parameter
    takes. One helper so the bench's per-LADDER-STAGE bytes accounting
    (ops/precision.py stages) cannot drift from the cost models: pass
    dtype_itemsize(stage.dtype) and the same analytic byte counts price f64
    polish sweeps at 8 B/elem and f32/bf16 hot sweeps at 4/2."""
    import numpy as np

    return int(np.dtype(dtype).itemsize)


@dataclasses.dataclass(frozen=True)
class ChipPeaks:
    matmul_flops: float   # headline matmul peak (bf16 for TPU), FLOP/s
    vpu_ops: float        # vector-unit elementwise peak, op/s (estimate)
    hbm_bytes: float      # HBM bandwidth, B/s


CHIP_PEAKS = {
    # TPU v5e: 197 TFLOP/s bf16, 819 GB/s HBM (public); VPU estimated (see
    # module docstring).
    "tpu": ChipPeaks(matmul_flops=1.97e14, vpu_ops=6.8e12, hbm_bytes=8.19e11),
}


@dataclasses.dataclass(frozen=True)
class KernelCost:
    mxu_flops: float
    vpu_ops: float
    hbm_bytes: float

    def __add__(self, other: "KernelCost") -> "KernelCost":
        return KernelCost(self.mxu_flops + other.mxu_flops,
                          self.vpu_ops + other.vpu_ops,
                          self.hbm_bytes + other.hbm_bytes)

    def __mul__(self, k: float) -> "KernelCost":
        return KernelCost(self.mxu_flops * k, self.vpu_ops * k, self.hbm_bytes * k)

    __rmul__ = __mul__


def vfi_sweep_cost(N: int, na: int, itemsize: int = 4) -> KernelCost:
    """One dense precomputed-U Bellman sweep (ops/bellman.py
    bellman_step_precomputed): EV = beta * P @ v ([N,N]x[N,na] matmul), then
    q = U + EV broadcast and a trailing-axis max+argmax over the [N, na, na']
    tensor. The U tensor read is the dominant HBM term — it is na x the size
    of every other operand and cannot be fused away (it is a solve-wide
    constant living in HBM at fine grids)."""
    mxu = 2.0 * N * N * na
    # add + max-compare + argmax-compare per (i, j, j') cell.
    vpu = 3.0 * N * na * na
    bytes_ = itemsize * (N * na * na      # U read
                         + 4.0 * N * na)  # v read, EV write/read, v_new+idx write
    return KernelCost(mxu, vpu, bytes_)


def vfi_slab_cost(N: int, na: int, itemsize: int = 4, *,
                  improve_rounds: int = 1, eval_sweeps: int = 0,
                  sq: int = 256, kb: int = 256, mw: int = 6) -> KernelCost:
    """Slab-argmax continuous VFI (solvers/vfi.solve_aiyagari_vfi_continuous,
    use_slab route): `improve_rounds` slab improvement rounds plus
    `eval_sweeps` Howard one-hot evaluation sweeps — the two passes share
    the slab geometry (sq-query blocks, mw contiguous kb-cell knot blocks =
    a sq*kb*mw/sq-wide candidate slab per query), so both are dominated by
    dense VPU work over N * ceil(na/sq) * sq * (kb*mw) slab cells.

    Per improvement cell: consumption (sub+clamp), CRRA u (pow+div ~3),
    + seg add, feasibility (3 compares + combine ~6), max-reduce compare,
    tie-to-previous argmin pass (~3) — ~16 ops. Per evaluation cell: the
    one-hot contraction's eq-compare + select + add — 3 ops. HBM: the slab
    block-DMA fetch (mw*kb cells per sq queries = mw*kb/sq bytes/query) plus
    ~6-8 [N, na] operand streams; both passes are an order of magnitude
    below the VPU term, which matches the measured bound (BENCHMARKS.md
    round 5). VFISolution.iterations / .eval_sweeps supply the two counts
    (final multiscale stage only — coarse-ladder stages are <10% of wall,
    same convention as the EGM model's use here)."""
    slab = float(kb * mw)
    nbp = -(-na // sq)
    cells = float(N) * nbp * sq * slab
    imp = KernelCost(
        mxu_flops=2.0 * N * N * na,
        vpu_ops=16.0 * cells,
        hbm_bytes=itemsize * (N * nbp * slab + 8.0 * N * na))
    ev = KernelCost(
        mxu_flops=2.0 * N * N * na,
        vpu_ops=3.0 * cells + float(N) * na,
        hbm_bytes=itemsize * (N * nbp * slab + 6.0 * N * na))
    return improve_rounds * imp + eval_sweeps * ev


def egm_sweep_cost(N: int, na: int, itemsize: int = 4,
                   windowed: bool | None = None,
                   qblock: int = 512, wblocks: int = 6) -> KernelCost:
    """One EGM sweep (ops/egm.egm_step): the Euler-RHS expectation matmul,
    ~15 elementwise ops per (state, grid) cell (u', u'^-1, endogenous grid,
    budget), a cummax, and the grid inversion. Inversion route per
    ops/interp.inverse_interp_power_grid:

      * dense (na <= INVERSE_DENSE_CUTOFF): 3 ops per [n_q, n_k] cell per row
        (compare + two masked reduces);
      * windowed: level-1 block locate (na/qblock rows x na knot compares)
        plus 3 ops per [n_q, window] cell, window = wblocks*qblock knots.

    HBM model: ~10 [N, na] arrays touched (iterate, RHS, endogenous grid,
    policies in/out) plus the windowed route's gathered knot slabs
    (wblocks/qblock-granular DMA: na * wblocks elements per row)."""
    from aiyagari_tpu.ops.interp import INVERSE_DENSE_CUTOFF

    if windowed is None:
        windowed = na > INVERSE_DENSE_CUTOFF
    mxu = 2.0 * N * N * na
    vpu = 15.0 * N * na + 2.0 * N * na     # elementwise + cummax
    bytes_ = itemsize * 10.0 * N * na
    if windowed:
        L = float(qblock * wblocks)
        nb = -(-na // qblock)
        vpu += N * (nb * float(na)         # level-1 locate
                    + 3.0 * L * na)        # windowed compare-reduce
        bytes_ += itemsize * N * (L * nb)  # window slab gathers
    else:
        vpu += 3.0 * N * float(na) * na
    return KernelCost(mxu, vpu, bytes_)


def egm_fused_sweep_cost(N: int, na: int, itemsize: int = 4, *,
                         block_q: int = 256, block_src: int = 256,
                         straddle_chunks: float = 2.0) -> KernelCost:
    """One fused Pallas EGM sweep (ops/pallas_egm.py): the whole
    interp→invert→update chain in one VMEM-resident pass.

    HBM model — the fused win this PR exists to price: C, a_grid and P are
    read ONCE (full-array blocks with constant index maps: the pipeline
    fetches them a single time and they stay resident across query-tile
    programs) and only the finished (C_new, policy_k) tiles are written —
    3 policy-sized streams + the grid + P, vs the XLA chain's ~10 streams
    (egm_sweep_cost). `itemsize` is the stage dtype's width, so ladder hot
    stages price at half the polish bytes exactly like the other models.

    Compute model — what the fusion pays for the single-read property,
    honestly: every query-tile program rebuilds the knot columns it needs
    from the resident C. Per program (na/block_q of them): the gate scan
    evaluates the EGM chain at 3 columns per na/block_src chunk — the two
    boundaries plus the columnwise C-max bound column (an [N,N]x[N,1]
    matvec + ~20 VPU ops per row each, plus the [N, block_src] max reduce
    that builds the bound) — and ~`straddle_chunks` chunks (the
    (1+r)-bounded
    knot/query density overlap of the EGM endogenous grid; a non-monotone
    pathological iterate just skips less, cf. the pallas push-forward
    model) pay the dense work: the chunk's full chain, the masked-reduce
    cummax ([N, block_src, block_src]) and the bracket compare-reduce
    (~6 ops per [N, block_src, block_q] cell). The chain recomputation is
    the deliberate trade — VPU/MXU work, which the starved MXU has to
    spare (BENCH_r08), for HBM bytes, which it does not."""
    S = float(min(block_q, max(na, 2)))
    CH = float(min(block_src, S))
    nt = float(-(-na // int(S)))
    nc = float(-(-na // int(CH)))
    gate_cols = 3.0 * nc + 2.0    # chunk boundaries + C-max bound + head
    mxu = nt * (2.0 * N * N * gate_cols
                + straddle_chunks * 2.0 * N * N * CH)
    vpu = nt * (20.0 * N * gate_cols
                + nc * N * CH                           # C-max gate reduce
                + straddle_chunks * (N * CH * CH        # masked cummax
                                     + 6.0 * N * CH * S  # bracket reduce
                                     + 20.0 * N * CH)    # chunk chain
                + 10.0 * N * S)                          # finish + budget
    bytes_ = itemsize * (3.0 * N * na        # C read; C_new + policy_k write
                         + na                # a_grid read (once)
                         + N * N             # P read (once)
                         + N)                # s read
    return KernelCost(mxu, vpu, bytes_)


def distribution_sweep_cost(N: int, na: int, itemsize: int = 8,
                            route: str = "scatter",
                            band_width: int = 256) -> KernelCost:
    """One Young push-forward sweep (ops/pushforward.py, any
    DistributionBackend, + the per-sweep renormalization): the lottery push
    along the asset axis, the [N,N]x[N,na] income-mixing matmul, and the
    sum/divide mass renormalization.

    Shared terms: the mixing matmul reads mu_a and writes mu_new, and the
    renormalize + distance reductions stream mu_new and the previous
    iterate once more. `route` prices the lottery push itself:

      * "scatter"   — reads mu + w_lo + the int32 idx stream (counted at
        4 B regardless of the float itemsize) and writes mu_a; 2 multiplies
        + 2 adds per cell. ~7 float [N, na] streams total — the memory-
        bound profile the mixed-precision ladder's f32 stage halves.
      * "transpose" — two cumsum passes over the leg products (read +
        write each), the bounds gathers (na log2(na) compares at plan
        build, amortized to ~log2(na)/sweep for per-step plans, counted
        here), and the gather/diff assembly: ~9 float streams and
        (6 + log2(na)) VPU ops per cell. No scatter anywhere.
      * "banded"    — the block-band apply: the dominant HBM term is the
        [N, na, band_width] band itself streamed once per sweep (it cannot
        stay resident at fine grids), plus the gathered source windows;
        MXU FLOPs are 2 * N * na * band_width for the band contraction on
        top of the mixing matmul. Trades bytes for MXU residency — the
        TPU-favorable exchange, honest-priced here so achieved GB/s does
        not flatter it.
      * "pallas"    — the fused kernel: mu/w_lo/idx read once, the mixed
        tile written once (~4 float streams + idx — the minimal-HBM
        route), but the in-VMEM compare-accumulate is dense over each
        overlapping [block_src, block_l] chunk: ~6 ops x 2 overlapping
        chunks x block_src (= 256) per OUTPUT cell under the monotone
        overlap model. The kernel deliberately trades VPU compares for
        zero scatter and minimal HBM traffic; the model says so.

    The bench prices each LADDER STAGE with its own itemsize
    (dtype_itemsize) and reports achieved GB/s per route and stage."""
    import math

    cells = float(N) * na
    mxu = 2.0 * N * N * na
    if route == "scatter":
        vpu = 7.0 * cells
        bytes_ = itemsize * 7.0 * cells + 4.0 * cells   # + int32 idx stream
    elif route == "transpose":
        vpu = (6.0 + math.log2(max(na, 2))) * cells
        bytes_ = itemsize * 9.0 * cells + 4.0 * cells
    elif route == "banded":
        bw = float(min(max(band_width, 1), na))
        mxu += 2.0 * cells * bw
        vpu = 5.0 * cells
        bytes_ = itemsize * (cells * bw     # the band, streamed per sweep
                             + bw / 128.0 * cells   # window gathers per tile
                             + 6.0 * cells)
    elif route == "pallas":
        vpu = (5.0 + 6.0 * 2.0 * 256.0) * cells   # dense in-VMEM compares
        bytes_ = itemsize * 4.0 * cells + 4.0 * cells
    else:
        raise ValueError(f"unknown pushforward route {route!r}")
    return KernelCost(mxu, vpu, bytes_)


def ge_fused_round_cost(N: int, na: int, itemsize: int = 8, *,
                        policy_sweeps: int = 1, dist_sweeps: int = 1,
                        route: str = "transpose",
                        batch: int = 1) -> KernelCost:
    """One OUTER round of the fused one-program GE loop
    (equilibrium/fused.py): `policy_sweeps` EGM sweeps at the round's
    prices, `dist_sweeps` push-forward sweeps to the stationary
    distribution, and the market-clearing tail — aggregation reductions
    over the [N, na] distribution/policy pair plus the O(1) price update
    and bracket arithmetic, counted as ~4 ops and 3 streamed arrays per
    cell. `batch` scales every term for the vmapped candidate round
    (fused_ge_batched_program), where B candidate rates run the same
    round in lockstep.

    Rounds-per-solve is data-dependent (the bisection/candidate loop exits
    on a traced predicate), so this prices one ROUND; the bench multiplies
    by the measured round count — attribution joins the fused programs
    unpriced for exactly that reason (attribution._model_prices)."""
    per_lane = (policy_sweeps * egm_sweep_cost(N, na, itemsize)
                + dist_sweeps * distribution_sweep_cost(N, na, itemsize,
                                                        route=route)
                + KernelCost(0.0, 4.0 * N * na,
                             itemsize * 3.0 * N * na))
    return max(batch, 1) * per_lane


def transition_fused_round_cost(N: int, na: int, T: int, itemsize: int = 8,
                                *, route: str = "transpose",
                                batch: int = 1) -> KernelCost:
    """One OUTER round of the fused one-program transition loop
    (transition/fused.py): the backward dated-EGM scan is T single EGM
    sweeps at the round's price path, the forward push is T distribution
    push-forward sweeps, and the tail is the Newton step — a [T, T]
    Jacobian-inverse matmul on the excess-demand vector (2*T*T MACs)
    plus the O(T) price-path arithmetic (excess demand, sup-norm, damped
    blend, clip — ~6 ops per period) streaming the [N, na] anchor pair
    and the [T, T] inverse. `batch` scales every term for the vmapped
    lockstep sweep (fused_transition_sweep_program), where S scenario
    lanes run the same round; the hoisted jac_inv is shared, but the
    model charges it per lane — at T << sqrt(N*na) the overcount is
    noise against the sweeps.

    Rounds-per-solve is data-dependent (the while_loop exits on the
    traced sup-norm predicate), so this prices one ROUND; the bench
    multiplies by the measured round count — attribution joins the fused
    transition programs unpriced for exactly that reason
    (attribution._model_prices)."""
    per_lane = (T * egm_sweep_cost(N, na, itemsize)
                + T * distribution_sweep_cost(N, na, itemsize, route=route)
                + KernelCost(2.0 * T * T,
                             4.0 * N * na + 6.0 * T,
                             itemsize * (3.0 * N * na + T * T)))
    return max(batch, 1) * per_lane


def mesh2d_collective_cost(S: int, N: int, na: int, *, scenarios: int,
                           grid: int, itemsize: int = 8, sweeps: int = 1,
                           rounds: int = 1,
                           devices_per_host: int | None = None) -> dict:
    """Cross-axis collective bytes of a 2-D (scenarios x grid) sweep —
    the price of composing both parallelism axes in one program, split by
    the link each axis actually rides so the scaling claim is certified,
    not asserted (ISSUE 13).

    Grid axis (ICI — a host's chips): per scenario lane per sweep, the
    ring-sharded EGM program's collectives (solvers/egm_sharded.py):

      * the ring slab rotation — (grid-1) ppermute hops each moving one
        [N, na/grid] knot shard,
      * the cummax-tail / head-pair all_gathers — O(grid * N) stacked
        rows,
      * the pmax'd sup-norm / escape / bracket-start reductions —
        O(grid) scalars.

    All of it multiplies by S lanes x `sweeps` (lanes are independent,
    so the 2-D program's ICI traffic is exactly S parallel copies of the
    1-D grid-sharded program's — nothing new crosses chips).

    Scenario axis (DCN — across hosts, when the mesh spans more than one):
    NOTHING per sweep — lanes never communicate — which is the design
    point: the only cross-host traffic is the per-ROUND lockstep
    synchronization (each host's per-lane gap/supply scalars read back
    for the host-side bracket update, 2 scalars per lane per round).
    `devices_per_host` defaults to the grid-axis size when grid > 1 (the
    natural pod layout: one host's chips = one lane's grid shards) and to
    the WHOLE mesh otherwise (a scenarios-only mesh on one host); a
    1-host topology prices dcn_bytes at 0.

    Lower-bound honesty at the degenerate sizes: a grid axis of 1 has NO
    grid collectives (every gather/reduce over a size-1 axis moves zero
    bytes), so a scenarios-only topology prices at exactly 0/0 on one
    host — the zero-communication claim, stated as a number rather than
    rounded up past it.

    Returns {"ici_bytes", "dcn_bytes", "ici_seconds", "dcn_seconds",
    "hosts", "grid_bytes_per_lane_sweep"} — bytes are analytic lower
    bounds (module docstring), seconds use the public-order interconnect
    peaks above (ICI_BYTES_PER_SEC / DCN_BYTES_PER_SEC)."""
    if scenarios < 1 or grid < 1:
        raise ValueError(
            f"mesh axes must be >= 1, got scenarios={scenarios} "
            f"grid={grid}")
    dph = (devices_per_host if devices_per_host
           else (grid if grid > 1 else scenarios * grid))
    hosts = -(-scenarios * grid // dph)
    per_lane_sweep = 0.0 if grid == 1 else itemsize * (
        (grid - 1) * float(N) * (na / grid)                 # ring rotation
        + 3.0 * grid * N                                    # tail/head gathers
        + float(grid))                                      # scalar reductions
    ici = float(S) * sweeps * per_lane_sweep
    dcn = (rounds * 2.0 * S * itemsize) if hosts > 1 else 0.0
    return {
        "ici_bytes": ici,
        "dcn_bytes": dcn,
        "ici_seconds": ici / ICI_BYTES_PER_SEC,
        "dcn_seconds": dcn / DCN_BYTES_PER_SEC,
        "hosts": int(hosts),
        "grid_bytes_per_lane_sweep": per_lane_sweep,
    }


def achieved_bandwidth_gbs(cost: KernelCost | None,
                           seconds: float) -> float | None:
    """Achieved memory bandwidth, GB/s, of `cost`'s modeled bytes moved in
    `seconds` — an ABSOLUTE number (unlike utilization's %-of-peak, it
    needs no chip model, so CPU-host bench runs report it too). Since the
    model bytes are analytic lower bounds (module docstring), this is a
    conservative achieved figure. None when the cost is unmodeled or the
    timing is degenerate."""
    if cost is None or seconds <= 0:
        return None
    return cost.hbm_bytes / seconds / 1e9


def panel_step_cost(population: int, ns: int = 4, nk: int = 100,
                    itemsize: int = 4, analytic: bool = False) -> KernelCost:
    """One Krusell-Smith panel step (sim/ks_panel._panel_scan).

    analytic=False models the one-hot route (ops/interp.
    state_policy_interp): per agent, a [1,ns]x[ns,nk] one-hot row pick
    (MXU), an nk-wide bucket one-hot + segment contraction (VPU); the HBM
    model assumes the [B, nk] one-hot and row-pick intermediates
    materialize once each (matmul operands, not fusable temporaries).

    analytic=True models the production power-grid route
    (state_policy_interp_power, the grid_power>0 path): the bucket and
    bracketing values are closed forms, no HIGHEST matmuls, and XLA fuses
    the hat-weighted reduction into ONE streamed [B, nk] pass — modeling
    the one-hot route's three materialized intermediates here overcounts
    bytes ~3x (observed: membw_frac 1.5 at 100k agents, a physically
    impossible fraction from the wrong model)."""
    mxu = (0.0 if analytic else 2.0 * population * ns * nk)   # ohS @ policies
    vpu = population * (ns + 7.0 * nk)     # weights/masks + reductions
    per_agent_bytes = (nk + 8.0) if analytic else (3.0 * nk + 8.0)
    bytes_ = itemsize * population * per_agent_bytes
    return KernelCost(mxu, vpu, bytes_)


def utilization(seconds: float, cost: KernelCost | None, platform: str = "tpu") -> dict:
    """Absolute utilization of `cost` executed in `seconds` on `platform`.

    Returns {"mfu", "vpu_frac", "membw_frac", "bound"} — mfu counts ALL
    model operations (MXU FLOPs + VPU ops) against the chip's headline
    matmul peak (the conventional MFU denominator; conservative for
    VPU-heavy kernels), vpu_frac counts VPU ops against the estimated VPU
    peak, membw_frac counts model bytes against HBM bandwidth. "bound" names
    the largest fraction — the resource the kernel is closest to saturating
    under this model. Unknown platforms (CPU fallback runs) return None
    fields so a JSON record never carries a made-up denominator; so does a
    None cost (kernels without an analytic model)."""
    peaks = CHIP_PEAKS.get(platform)
    if peaks is None or cost is None or seconds <= 0:
        return {"mfu": None, "vpu_frac": None, "membw_frac": None, "bound": None}
    mfu = (cost.mxu_flops + cost.vpu_ops) / (seconds * peaks.matmul_flops)
    vpu_frac = cost.vpu_ops / (seconds * peaks.vpu_ops)
    membw_frac = cost.hbm_bytes / (seconds * peaks.hbm_bytes)
    fracs = {"mxu": cost.mxu_flops / (seconds * peaks.matmul_flops),
             "vpu": vpu_frac, "hbm": membw_frac}
    return {
        "mfu": round(mfu, 4),
        "vpu_frac": round(vpu_frac, 4),
        "membw_frac": round(membw_frac, 4),
        "bound": max(fracs, key=fracs.get),
    }
