"""Mesh skew & straggler probes: fenced per-axis rendezvous timings around
mesh activation (ISSUE 14 tentpole).

A 2-D (scenarios x grid) sweep forces a mesh-wide collective rendezvous
every sweep (the lane_sync_axis trip-count pmax, DESIGN.md §4a): on a pod,
ONE slow host stalls every chip, and nothing in the post-hoc ledger says
which host it was or whether the wall was DCN sync rather than compute.
These probes answer that at the only place it can be answered cheaply —
the dispatch boundary, once per mesh activation, NOT inside the solve loop
(a per-sweep probe would itself be a host sync inside a hot loop, exactly
what rule AIYA103 forbids; DESIGN.md "Why skew probes live at the dispatch
boundary").

Per mesh axis, the probe times a fenced psum rendezvous over that axis
alone (interleaved best-of-reps with rotated order — the PR 6/10
one-burst-skews-a-ratio lesson), gathers every host's arrival lag (the
host-side delay reaching the rendezvous, a per-host duration so clock
offsets cancel), and renders a straggler verdict when one host's lag
exceeds the configured band. Each axis emits one `host_skew` ledger event
plus an `aiyagari_host_skew_seconds{axis=}` gauge; when the caller prices
the sweep (dispatch.sweep passes its S/N/na), the event carries a
reconciliation row against `roofline.mesh2d_collective_cost`'s priced
ICI/DCN sync so measured-vs-modeled is one comparison, not two artifacts.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

__all__ = ["SkewConfig", "probe_mesh_skew", "straggler_verdict"]


@dataclasses.dataclass(frozen=True)
class SkewConfig:
    """Knobs for one probe pass.

    reps: fenced rendezvous repetitions per axis (interleaved, best-of).
    straggler_band_seconds: absolute arrival-lag spread floor below which
        no host is ever called a straggler (scheduler noise).
    straggler_band_factor: relative band — a host must lag the median by
        more than factor x the measured rendezvous itself."""

    reps: int = 5
    straggler_band_seconds: float = 5e-3
    straggler_band_factor: float = 3.0

    def __post_init__(self):
        if self.reps < 1:
            raise ValueError(f"SkewConfig.reps must be >= 1, got {self.reps}")
        if self.straggler_band_seconds < 0 or self.straggler_band_factor < 0:
            raise ValueError("SkewConfig straggler bands must be >= 0")


def straggler_verdict(lags, rendezvous_seconds: float,
                      config: SkewConfig = SkewConfig()) -> dict:
    """The per-axis verdict from every host's arrival lag: "straggler"
    (naming the host) when the worst lag exceeds the median by more than
    the band — max(absolute floor, factor x measured rendezvous) — else
    "balanced". Pure so the band logic is unit-testable with synthetic
    multi-host lags."""
    lags = np.asarray(lags, np.float64).reshape(-1)
    if lags.size == 0:
        return {"verdict": "balanced", "straggler": None,
                "lag_spread_seconds": 0.0}
    spread = float(np.max(lags) - np.median(lags))
    band = max(config.straggler_band_seconds,
               config.straggler_band_factor * float(rendezvous_seconds))
    if lags.size > 1 and spread > band:
        return {"verdict": "straggler", "straggler": int(np.argmax(lags)),
                "lag_spread_seconds": round(spread, 6),
                "band_seconds": round(band, 6)}
    return {"verdict": "balanced", "straggler": None,
            "lag_spread_seconds": round(spread, 6),
            "band_seconds": round(band, 6)}


def _gather_host_lags(my_lag: float) -> list:
    """Every host's arrival lag, index = process id. Single-process (the
    virtual-device mesh) is just this host; multi-process rides the same
    SPMD allgather channel the mesh programs use."""
    import jax

    from aiyagari_tpu.parallel.distributed import peek_process_topology

    _, count = peek_process_topology()
    if count <= 1:
        return [float(my_lag)]
    from jax.experimental import multihost_utils  # pragma: no cover - pod

    import jax.numpy as jnp

    gathered = multihost_utils.process_allgather(
        jnp.asarray([my_lag], jnp.float32))
    return [float(x) for x in np.ravel(np.asarray(gathered))]


def _reconcile(axis: str, measured_s: float, price: dict) -> Optional[dict]:
    """The measured-vs-priced row: the scenario axis's rendezvous against
    mesh2d_collective_cost's per-round DCN sync, the grid axis's against
    its per-lane-sweep ICI bytes. ratio None when the priced side is zero
    (a one-host topology prices DCN at exactly 0 — the honest degenerate
    case)."""
    from aiyagari_tpu.diagnostics.roofline import (
        ICI_BYTES_PER_SEC,
        mesh2d_collective_cost,
    )

    cost = mesh2d_collective_cost(
        price["S"], price["N"], price["na"],
        scenarios=price["scenarios"], grid=price["grid"],
        itemsize=price.get("itemsize", 8), sweeps=1, rounds=1)
    if axis == "scenarios":
        priced = cost["dcn_seconds"]
        link = "dcn"
    else:
        priced = cost["grid_bytes_per_lane_sweep"] / ICI_BYTES_PER_SEC
        link = "ici"
    return {
        "link": link,
        "hosts": cost["hosts"],
        "measured_seconds": round(measured_s, 6),
        "priced_seconds": priced,
        "ratio": (round(measured_s / priced, 2) if priced > 0 else None),
    }


def probe_mesh_skew(mesh, *, config: SkewConfig = SkewConfig(),
                    price: Optional[dict] = None, ledger=None,
                    emit: bool = True) -> dict:
    """Time one fenced psum rendezvous per mesh axis and judge host skew.

    Returns {"axes": [per-axis records], "mesh": {axis: size},
    "processes": P}; each axis record carries the best-of-reps rendezvous
    wall, every host's arrival lag, the straggler verdict, and (with
    `price` = {"S", "N", "na"[, "scenarios", "grid", "itemsize"]} — the
    axis sizes default to the mesh's own) the reconciliation row against
    the roofline's priced collectives. With `emit`, each axis lands a
    `host_skew` event on `ledger` (or the active ledger) and sets
    aiyagari_host_skew_seconds{axis=}."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.diagnostics import metrics
    from aiyagari_tpu.diagnostics import ledger as ledger_mod
    from aiyagari_tpu.diagnostics.profiler import fence
    from aiyagari_tpu.parallel.distributed import peek_process_topology
    from aiyagari_tpu.parallel.mesh import PartitionSpec as P, shard_map

    axes = list(mesh.axis_names)
    if price is not None:
        price = {"scenarios": int(mesh.shape.get("scenarios", 1)),
                 "grid": int(mesh.shape.get("grid", 1)), **price}
    fns, xs = {}, {}
    for ax in axes:
        def _body(x, _ax=ax):
            return jax.lax.psum(x, _ax)

        fns[ax] = jax.jit(shard_map(_body, mesh=mesh,
                                    in_specs=P(ax), out_specs=P()))
        xs[ax] = jnp.arange(int(mesh.shape[ax]), dtype=jnp.float32)
    # Compile outside the timed reps: the probe measures rendezvous, not
    # tracing.
    for ax in axes:
        fence(fns[ax](xs[ax]))
    walls: dict = {ax: [] for ax in axes}
    lags: dict = {ax: [] for ax in axes}
    t_prev = time.perf_counter()
    for rep in range(config.reps):
        k = rep % len(axes)
        for ax in axes[k:] + axes[:k]:
            t_arrive = time.perf_counter()
            out = fns[ax](xs[ax])
            fence(out)
            t_done = time.perf_counter()
            walls[ax].append(t_done - t_arrive)
            # Host-side delay from the previous barrier's completion to
            # this dispatch: the previous fenced collective synchronizes
            # every host, so this duration is comparable across hosts
            # without clock sync.
            lags[ax].append(t_arrive - t_prev)
            t_prev = t_done

    _, processes = peek_process_topology()
    records = []
    for ax in axes:
        best = float(np.min(walls[ax]))
        host_lags = _gather_host_lags(float(np.median(lags[ax])))
        rec = {
            "axis": ax,
            "size": int(mesh.shape[ax]),
            "rendezvous_seconds": round(best, 6),
            "mean_seconds": round(float(np.mean(walls[ax])), 6),
            "reps": config.reps,
            "processes": processes,
            "arrival_lag_seconds": [round(v, 6) for v in host_lags],
            **straggler_verdict(host_lags, best, config),
        }
        if price is not None:
            rec["reconciliation"] = _reconcile(ax, best, price)
        metrics.gauge("aiyagari_host_skew_seconds", axis=ax).set(best)
        if emit:
            if ledger is not None:
                ledger.event("host_skew", **rec)
            else:
                ledger_mod.emit("host_skew", **rec)
        records.append(rec)
    return {
        "axes": records,
        "mesh": {name: int(mesh.shape[name]) for name in axes},
        "processes": processes,
    }
