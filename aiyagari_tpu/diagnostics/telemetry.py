"""Device-resident flight recorders for the hot fixed-point loops.

Every solver in this framework is a `lax.while_loop` whose residual
trajectory normally dies inside the loop: the host sees terminal scalars
(`iterations`, `distance`, `hot_iterations`) and nothing else. Den Haan's
accuracy-testing program (PAPERS.md) treats the error TRAJECTORY, not the
endpoint, as the correctness certificate — a solve that limit-cycles at
1.1x tol for 900 sweeps and one that decays geometrically to tol/100 report
the same terminal scalars today. This module makes the trajectory a
first-class output:

  * `SolveTelemetry` is a small pytree of fixed-length ring buffers carried
    INSIDE the while_loop body (residual + stage-dtype per sweep, accel
    safeguard trips, push-forward fallback tallies). No host callbacks, no
    sync, no dynamic shapes — it jits, vmaps (one recorder per scenario in
    the batched-GE/sweep programs), and shard_maps (replicated: every
    device records the pmax'd global residual, so the buffers agree).
  * The recorder functions (`telemetry_record`, ...) are COMPILE-TIME
    no-ops when the recorder is None: they return their None unchanged, so
    a telemetry-off solve traces to the identical program and carries zero
    extra bytes (pinned by tests/test_telemetry.py's jaxpr assertion).
  * Ring, not head-truncated: a loop longer than `capacity` keeps the LAST
    `capacity` residuals — the tail is what the stall/oscillation
    certificates (diagnostics/health.py) read — while `count` keeps the
    true total so truncation is visible.

The user-facing knob is `config.TelemetryConfig` (frozen/hashable, a jit
static arg), wired as `SolverConfig(telemetry=...)` through every solver
family; host-side outer loops (GE bisection, KS ALM, transition rounds)
assemble the same pytree from their per-round records via
`host_telemetry`, so one shape serves both worlds.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import TelemetryConfig

__all__ = [
    "SolveTelemetry",
    "TelemetryConfig",
    "telemetry_init",
    "telemetry_record",
    "telemetry_set_trips",
    "telemetry_add_fallbacks",
    "telemetry_leaves",
    "telemetry_from_leaves",
    "host_telemetry",
    "telemetry_trajectory",
    "telemetry_stages",
    "telemetry_summary",
]

# Residuals are recorded in ONE dtype regardless of the sweep's stage dtype
# (the mixed-precision ladder changes the carry dtype mid-solve, and the
# recorder must cross that stage boundary without changing pytree structure).
# f32 resolves any residual the stopping rules can distinguish (min normal
# ~1e-38 vs tolerances >= 1e-16) at half the carry bytes of f64.
_RES_DTYPE = jnp.float32


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SolveTelemetry:
    """One solve's flight record. All fields are arrays (pytree leaves), so
    the record vmaps/shards with the solve that produced it; under a
    scenario batch every field carries a leading [S] axis."""

    residuals: jax.Array   # [capacity] f32 ring of per-sweep residuals
    stage_bits: jax.Array  # [capacity] int32 dtype width of each sweep (32/64)
    count: jax.Array       # int32 total sweeps recorded (may exceed capacity)
    accel_trips: jax.Array     # int32 safeguard fallbacks (ops/accel.py)
    fallbacks: jax.Array       # int32 push-forward degradations (ops/pushforward.py)

    @property
    def capacity(self) -> int:
        return int(self.residuals.shape[-1])


def telemetry_init(cfg: Optional[TelemetryConfig],
                   dtype=None) -> Optional[SolveTelemetry]:
    """A fresh recorder for `cfg`, or None when telemetry is off — the None
    flows through every recorder call unchanged, so the off path compiles
    to the exact pre-telemetry program."""
    if cfg is None:
        return None
    cap = int(cfg.capacity)
    if cap < 1:
        raise ValueError(f"TelemetryConfig.capacity must be >= 1, got {cap}")
    return SolveTelemetry(
        residuals=jnp.full((cap,), jnp.nan, _RES_DTYPE),
        stage_bits=jnp.zeros((cap,), jnp.int32),
        count=jnp.int32(0),
        accel_trips=jnp.int32(0),
        fallbacks=jnp.int32(0),
    )


def telemetry_record(tele: Optional[SolveTelemetry],
                     residual) -> Optional[SolveTelemetry]:
    """Record one sweep's residual into the ring (position count % capacity).
    The stage dtype is read off the residual's own dtype at TRACE time, so
    each ladder stage stamps its entries statically. No-op when off."""
    if tele is None:
        return None
    cap = tele.capacity
    pos = tele.count % cap
    bits = jnp.int32(jnp.finfo(jnp.asarray(residual).dtype).bits)
    return dataclasses.replace(
        tele,
        residuals=tele.residuals.at[pos].set(
            jnp.asarray(residual).astype(_RES_DTYPE)),
        stage_bits=tele.stage_bits.at[pos].set(bits),
        count=tele.count + 1,
    )


def telemetry_set_trips(tele: Optional[SolveTelemetry],
                        trips) -> Optional[SolveTelemetry]:
    """Overwrite the accel-safeguard trip count (callers pass a running
    total: stage base + the accel state's own counter). No-op when off."""
    if tele is None:
        return None
    return dataclasses.replace(tele,
                               accel_trips=jnp.asarray(trips, jnp.int32))


def telemetry_add_fallbacks(tele: Optional[SolveTelemetry],
                            n) -> Optional[SolveTelemetry]:
    """Add `n` push-forward degradation events (a traced int — plan-validity
    flags compile in, ops/pushforward.py). No-op when off."""
    if tele is None:
        return None
    return dataclasses.replace(
        tele, fallbacks=tele.fallbacks + jnp.asarray(n, jnp.int32))


# shard_map carries: the recorder crosses the shard_map boundary as a flat
# tuple of leaves (explicit out_specs per leaf — no pytree-prefix magic on
# the jax-0.4.x shim), reassembled by the host wrapper.
_N_LEAVES = 5


def telemetry_leaves(tele: Optional[SolveTelemetry]) -> tuple:
    """Flatten to a static-length tuple of arrays (empty when off)."""
    if tele is None:
        return ()
    return (tele.residuals, tele.stage_bits, tele.count, tele.accel_trips,
            tele.fallbacks)


def telemetry_from_leaves(leaves) -> Optional[SolveTelemetry]:
    """Inverse of telemetry_leaves."""
    if not leaves:
        return None
    assert len(leaves) == _N_LEAVES
    return SolveTelemetry(*leaves)


def host_telemetry(residuals, stage_bits=None, *, trips: int = 0,
                   fallbacks: int = 0) -> SolveTelemetry:
    """Assemble a SolveTelemetry from HOST-side per-round records — the
    outer loops (GE bisection rounds, KS ALM iterations, transition Newton
    rounds) already collect their residual histories as Python lists; this
    puts them in the same shape the device recorders return, so one report
    path serves both. Host numpy arrays, no device transfer."""
    res = np.asarray(residuals, np.float32).reshape(-1)
    cap = max(len(res), 1)
    buf = np.full(cap, np.nan, np.float32)
    buf[: len(res)] = res
    if stage_bits is None:
        bits = np.full(cap, 64, np.int32)
        bits[len(res):] = 0
    else:
        bits = np.zeros(cap, np.int32)
        bits[: len(res)] = np.asarray(stage_bits, np.int32).reshape(-1)[: len(res)]
    return SolveTelemetry(
        residuals=buf,
        stage_bits=bits,
        count=np.int32(len(res)),
        accel_trips=np.int32(trips),
        fallbacks=np.int32(fallbacks),
    )


def _host(tele: SolveTelemetry) -> SolveTelemetry:
    """One batched device fetch of every leaf (numpy out)."""
    leaves = [tele.residuals, tele.stage_bits, tele.count,
              tele.accel_trips, tele.fallbacks]
    if any(isinstance(l, jax.Array) for l in leaves):
        leaves = jax.device_get(leaves)
    return SolveTelemetry(*[np.asarray(l) for l in leaves])


def telemetry_trajectory(tele: SolveTelemetry) -> np.ndarray:
    """The chronological residual trajectory (host float32 array): the ring
    unrolled so index 0 is the OLDEST retained sweep. When count exceeded
    capacity, the head of the trajectory was overwritten — only the last
    `capacity` residuals exist (by design; `count` tells the truth)."""
    t = _host(tele)
    if t.residuals.ndim != 1:
        raise ValueError(
            "telemetry_trajectory reads ONE recorder; index a batched "
            f"telemetry (shape {t.residuals.shape}) down to one scenario "
            "first")
    cap = t.residuals.shape[0]
    n = int(t.count)
    if n <= cap:
        return t.residuals[:n]
    return np.roll(t.residuals, -(n % cap))


def telemetry_stages(tele: SolveTelemetry) -> np.ndarray:
    """Chronological stage-dtype widths aligned with telemetry_trajectory."""
    t = _host(tele)
    cap = t.stage_bits.shape[0]
    n = int(t.count)
    if n <= cap:
        return t.stage_bits[:n]
    return np.roll(t.stage_bits, -(n % cap))


def telemetry_summary(tele: Optional[SolveTelemetry]) -> Optional[dict]:
    """JSON-ready summary of one recorder — what the run ledger stores per
    solve (the full ring stays on the Solution for callers that want it)."""
    if tele is None:
        return None
    traj = telemetry_trajectory(tele)
    t = _host(tele)
    finite = traj[np.isfinite(traj)]
    switch = int(np.argmax(telemetry_stages(tele) ==
                           np.max(t.stage_bits))) if len(traj) else 0
    return {
        "sweeps": int(t.count),
        "retained": int(len(traj)),
        "capacity": int(t.residuals.shape[-1]),
        "first_residual": float(finite[0]) if len(finite) else None,
        "final_residual": float(finite[-1]) if len(finite) else None,
        "min_residual": float(finite.min()) if len(finite) else None,
        "stage_switch_at": switch,
        "accel_trips": int(t.accel_trips),
        "pushforward_fallbacks": int(t.fallbacks),
    }
