"""Deterministic fault injection for the resilience machinery.

Every recovery path this framework ships — the sentinel verdicts, the
NaN-exiting loop conds, the escape retries, the push-forward fallback
counting, scenario quarantine, the rescue ladder — is a path that NEVER
runs on healthy inputs, which means CI would never exercise it and it
would rot silently. This module is the antidote: a catalogue of opt-in,
compile-time injection points (`config.FaultPlan`) that produce a
specific, reproducible failure exactly where the corresponding recovery
path watches for one.

Design constraints (and why the helpers look the way they do):

  * Injections must reach INSIDE jit-compiled while-loop bodies without
    breaking the jit cache — so the plan is a frozen/hashable dataclass
    threaded as a STATIC argument through the same plumbing as
    TelemetryConfig (`SolverConfig(faults=...)`), never a mutable global a
    cached trace could go stale against.
  * A `None` (or default) plan must be a compile-time no-op: every helper
    returns its inputs unchanged, so production programs are bit-identical
    to a tree with no fault module at all.
  * Injections are deterministic: `nan_sweep=k` poisons sweep k every run;
    there is no randomness to make a recovery test flake.

The injection-point catalogue lives on the FaultPlan docstring
(aiyagari_tpu/config.py) and in docs/USAGE.md; bench.py's
`--metric resilience` battery drives every point through its recovery
path and tests/test_bench_ci.py gates 100% recovery.
"""

from __future__ import annotations

from typing import Optional

from aiyagari_tpu.config import FaultPlan

__all__ = [
    "FaultPlan",
    "poison_iterate",
    "force_escape_point",
    "forces_fallback",
    "poison_scenario_index",
    "stage_fails",
]


def _off(plan: Optional[FaultPlan]) -> bool:
    return plan is None


def poison_iterate(plan: Optional[FaultPlan], x, it):
    """Inject NaN into a solver iterate at sweep `plan.nan_sweep` (traced
    counter `it`, 0-based). Compile-time no-op unless the plan sets
    nan_sweep >= 0 — the traced program is unchanged."""
    if _off(plan) or plan.nan_sweep < 0:
        return x
    import jax.numpy as jnp

    return jnp.where(jnp.asarray(it) == plan.nan_sweep, jnp.nan, x)


def force_escape_point(plan: Optional[FaultPlan], x, escaped):
    """Force the EGM windowed-inversion escape contract: the iterate is
    NaN-poisoned AND the escape flag raised, exactly as
    ops/interp.inverse_interp_power_grid does when its static windows
    cannot cover the knot density. Compile-time no-op unless forced."""
    if _off(plan) or not plan.force_escape:
        return x, escaped
    import jax.numpy as jnp

    return jnp.full_like(x, jnp.nan), jnp.ones_like(escaped)


def forces_fallback(plan: Optional[FaultPlan]) -> bool:
    """Trace-time switch: should the distribution loop's push-forward plan
    be forced invalid (every sweep takes the compiled-in scatter fallback
    and tallies a degradation)?"""
    return not _off(plan) and plan.force_fallback


def poison_scenario_index(plan: Optional[FaultPlan]) -> Optional[int]:
    """The scenario index a sweep batch should poison (host-level: the
    stacked preference operand is NaN'd for that lane), or None."""
    if _off(plan) or plan.poison_scenario < 0:
        return None
    return int(plan.poison_scenario)


def stage_fails(plan: Optional[FaultPlan], stage: str) -> bool:
    """Should the rescue driver treat this ladder stage as failed without
    running it? (`fail_stage` is a comma-separated stage-name list.)"""
    if _off(plan) or not plan.fail_stage:
        return False
    return stage in {s.strip() for s in plan.fail_stage.split(",")}
