"""Bench-history regression watchdog: diff fresh benchmark records against
the frozen BENCH_r*.json trajectory (ISSUE 14 satellite).

Every bench round freezes artifacts (BENCHMARKS.md), but nothing ever READ
them back — a structural regression (a parity band blown, a fusion flag
raised, an attribution table shrinking, a formerly-working metric now
OOM-skipping) only surfaced when a human diffed the JSON. This module is
the automatic reader: `load_history()` collects every frozen record by
metric name, `check_records(fresh)` matches fresh records against the
newest frozen record of the SAME metric name and flags fields outside
their per-metric noise band.

Two severities, because the frozen trajectory mixes quiet-box full-size
rounds with ci-produced artifacts:

  structural — scale-independent claims (parities, bitwise pins, table
      sizes, flag lists, skip status). Checked whenever metric names
      match; gated at ZERO by tests/test_bench_ci.py.
  wall — absolute timings. Checked ONLY when the record's sizing fields
      (the check's `match` keys: grid, rounds, ...) are equal between
      fresh and frozen — a ci battery must never be timed against a
      full-size round — and with a deliberately catastrophic band (10x):
      the one-core host's scheduler noise is measured at 13%+, so walls
      here catch an accidental host sync, not a wiggle.

`bench.py --check-history` (on in `--preset ci`) runs this after the
battery, emits one `bench_regression` ledger event per finding, and prints
a `bench_history_check` record whose value is the finding count.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Optional

__all__ = ["Check", "check_records", "default_bench_dir", "load_history"]

# Catastrophe band for wall checks (see module docstring).
_WALL_BAND = 10.0


@dataclasses.dataclass(frozen=True)
class Check:
    """One banded field comparison. `field` is a dotted path into the
    record; kinds:

      bool      — frozen truthy => fresh must stay truthy
      empty     — fresh list/dict must stay empty when frozen was empty
      count_min — fresh numeric >= frozen / band
      keys_min  — fresh dict keys must cover the frozen dict's keys
      max_abs   — fresh numeric <= max(band * frozen, floor)  (parities)
      wall      — fresh numeric <= band * frozen, only when every `match`
                  sizing key is equal between the records
    """

    field: str
    kind: str
    band: float = 1.0
    floor: float = 0.0
    match: tuple = ()

    @property
    def structural(self) -> bool:
        return self.kind != "wall"


def _mesh2d_checks() -> tuple:
    out = [Check("value", "wall", band=_WALL_BAND,
                 match=("grid", "rounds", "scenarios", "devices"))]
    for topo in ("scenarios8", "grid8", "2x4"):
        out.append(Check(f"topologies.{topo}.r_equal", "bool"))
        out.append(Check(f"topologies.{topo}.parity_vs_unsharded",
                         "max_abs", band=1.0, floor=1e-10))
    return tuple(out)


# Per-metric-name-PREFIX check specs (metric names carry grid sizes; the
# history match itself is by exact name, so a ci-sized record never meets
# a full-size one — the prefix only selects which checks apply).
SPECS = {
    "mesh2d_sweep": _mesh2d_checks(),
    "route_attribution": (
        Check("value", "count_min"),
        Check("flagged", "empty"),
        Check("programs", "keys_min"),
        Check("knobs", "keys_min"),
    ),
    "pod_observatory": (
        Check("merge.ordered", "bool"),
        Check("merge.run_joined", "bool"),
        Check("merge.shards", "count_min"),
        Check("heartbeat.off_jaxpr_identical", "bool"),
        Check("heartbeat.off_bit_identical", "bool"),
        Check("skew.axes", "keys_min"),
        Check("value", "wall", band=_WALL_BAND,
              match=("devices", "scenarios")),
    ),
    "telemetry_recorder": (
        Check("off_bit_identical", "bool"),
        Check("off_jaxpr_noop", "bool"),
    ),
    "pushforward_sweep": (
        Check("routes", "keys_min"),
        Check("vs_baseline", "count_min", band=1.5),
    ),
    "egm_fused_sweep": (
        Check("routes", "keys_min"),
        Check("parity_vs_xla", "max_abs", band=10.0, floor=1e-9),
    ),
    "static_analysis_findings": (
        Check("value", "max_abs", band=1.0, floor=0.0),
    ),
    "transition_fused": (
        # One-program transitions (ISSUE 19). The device-over-host wall
        # ratio is held at the 0.8 acceptance ceiling (the hard gate runs
        # every ci battery in tests/test_bench_ci.py at the same
        # threshold); the host/device price paths must keep agreeing to
        # round-off; the r-path carry donation must keep actually
        # happening; and the structural launch collapse (ONE program per
        # solve) is a hard pin. The absolute wall rides the catastrophe
        # band, sized by the record's geometry + round count.
        Check("wall_ratio_device_over_host", "max_abs", band=1.0,
              floor=0.8),
        Check("r_agreement", "max_abs", band=1.0, floor=1e-10),
        Check("donated_input_deleted", "bool"),
        Check("device_converged", "bool"),
        Check("device_programs_fused", "max_abs", band=1.0, floor=1.0),
        Check("value", "wall", band=_WALL_BAND,
              match=("grid", "T", "device_rounds")),
    ),
    "serve_load": (
        # Structural: the regimes/ledger-trail/gauge surfaces must not
        # shrink, and the two acceptance ratios hold with bands wide
        # enough for host timing noise (the hard gates live in
        # tests/test_bench_ci.py at the same thresholds every run).
        Check("regimes", "keys_min"),
        Check("ledger_events", "keys_min"),
        Check("prometheus_gauges", "keys_min"),
        Check("warm_vs_cold_p50", "max_abs", band=1.0, floor=0.5),
        Check("coalesced_vs_serial", "count_min", band=2.0),
        # value is a THROUGHPUT (requests/sec — higher is better), so the
        # catastrophe band is a count_min at 10x, not a wall check.
        Check("value", "count_min", band=_WALL_BAND),
    ),
    "serve_amortized": (
        # The amortization layer (ISSUE 16). value IS the cold-solve
        # fraction — lower is better, so the direction is a max_abs held
        # at the acceptance ceiling (floor 0.5), not a count_min; the
        # per-predictor source tables and the ledger trail must not lose
        # keys; degraded guesses must NEVER change an answer (floor 0 =
        # hard zero); and the two warm-vs-cold latency ratios hold at the
        # acceptance bands (the hard gates run every ci battery in
        # tests/test_bench_ci.py at these same thresholds).
        Check("warm_sources", "keys_min"),
        Check("steady_by_source", "keys_min"),
        Check("transition_by_source", "keys_min"),
        Check("ledger_events", "keys_min"),
        Check("value", "max_abs", band=1.0, floor=0.5),
        Check("wrong_answer_degradations", "max_abs", band=1.0,
              floor=0.0),
        Check("surrogate_vs_cold_p50", "max_abs", band=1.0, floor=0.6),
        Check("anchor_warm_vs_cold_p50", "max_abs", band=1.0, floor=0.6),
    ),
    "fleet": (
        # The solve fabric (ISSUE 20). All four acceptance gates are
        # frozen as booleans (the hard gates run every ci battery in
        # tests/test_bench_ci.py at the same thresholds); poisoned-L2
        # wrong answers are a hard zero; the AOT restore ratio holds the
        # 0.5 acceptance ceiling; the 2-worker aggregate holds the 1.6x
        # floor as a count_min. value is the aggregate hit throughput
        # (requests/sec — higher is better), so its catastrophe band is
        # a count_min, not a wall check.
        Check("gates.aot_restore_le_half_fresh", "bool"),
        Check("gates.aggregate_ge_1p6x_single", "bool"),
        Check("gates.l2_cold_fraction_below", "bool"),
        Check("gates.poisoned_l2_degrades_bitwise", "bool"),
        Check("poisoned_l2.wrong_answer_degradations", "max_abs",
              band=1.0, floor=0.0),
        Check("aot_walls.worst_restore_vs_fresh", "max_abs", band=1.0,
              floor=0.5),
        Check("throughput.aggregate_vs_single", "count_min", band=1.6),
        Check("value", "count_min", band=_WALL_BAND),
    ),
    "calibration_recovery": (
        # The differentiable solve stack (ISSUE 17). value IS the planted-
        # parameter recovery error — the acceptance ceiling is 1e-3 and
        # the measured landing is ~1e-11, so the floor holds the hard
        # bound with ~8 orders of headroom; the adjoint-vs-finite-
        # difference parity holds at its own measured-plus-margin floor;
        # the fit must stay "converged" and keep all four parameters.
        Check("converged", "bool"),
        Check("params", "keys_min"),
        Check("value", "max_abs", band=1.0, floor=1e-3),
        Check("grad_fd_max_rel_err", "max_abs", band=1.0, floor=1e-4),
        Check("wall_per_gradient_seconds", "wall", band=_WALL_BAND,
              match=("grid", "n_states", "lanes")),
    ),
}


def default_bench_dir() -> str:
    """The repo root (where bench.py freezes its BENCH_r*.json)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_history(bench_dir: Optional[str] = None) -> dict:
    """Every frozen record, keyed by metric name: {metric: [{"record",
    "source"}, ...]} in round order (filename sort). Handles both frozen
    shapes: the modern flat record and the early rounds' {"parsed":
    <record>} wrapper. Unreadable files are skipped (history is advisory
    input, not a crash surface) — but an empty history is loud at the
    check level via the matched-metrics count."""
    bench_dir = bench_dir or default_bench_dir()
    out: dict = {}
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rec = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else doc
        name = rec.get("metric") if isinstance(rec, dict) else None
        if name:
            out.setdefault(name, []).append(
                {"record": rec, "source": os.path.basename(path)})
    return out


def _get(record: dict, dotted: str):
    cur = record
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _check_one(check: Check, fresh: dict, frozen: dict) -> Optional[str]:
    """None when inside the band, else a short reason."""
    fz = _get(frozen, check.field)
    if fz is None:
        return None          # older rounds predate the field: nothing to hold
    fr = _get(fresh, check.field)
    if check.kind == "wall":
        if any(_get(fresh, k) != _get(frozen, k) for k in check.match):
            return None      # different sizing: walls are incomparable
        if fr is None:
            return "wall field vanished"
        if float(fr) > check.band * float(fz):
            return (f"wall {fr} > {check.band}x frozen {fz}")
        return None
    if fr is None:
        return "field vanished from the fresh record"
    if check.kind == "bool":
        return None if (not fz or bool(fr)) else f"was {fz}, now {fr}"
    if check.kind == "empty":
        return (None if (len(fz) > 0 or len(fr) == 0)
                else f"was empty, now {fr}")
    if check.kind == "count_min":
        return (None if float(fr) >= float(fz) / check.band
                else f"{fr} < frozen {fz} / band {check.band}")
    if check.kind == "keys_min":
        missing = sorted(set(fz) - set(fr))
        return None if not missing else f"lost keys {missing}"
    if check.kind == "max_abs":
        bound = max(check.band * float(fz), check.floor)
        return (None if abs(float(fr)) <= bound
                else f"|{fr}| > max({check.band}x frozen {fz}, "
                     f"{check.floor})")
    raise ValueError(f"unknown check kind {check.kind!r}")


def _specs_for(metric: str) -> tuple:
    for prefix, checks in SPECS.items():
        if metric.startswith(prefix):
            return checks
    return ()


def check_records(records, *, history: Optional[dict] = None,
                  bench_dir: Optional[str] = None) -> tuple:
    """Diff `records` (this battery's fresh metric records) against the
    frozen history. Returns (findings, matched): `findings` is a list of
    {"metric", "field", "kind", "severity", "reason", "fresh", "frozen",
    "source"} dicts (empty on a healthy tree), `matched` the sorted metric
    names that had a frozen counterpart."""
    if history is None:
        history = load_history(bench_dir)
    findings: list = []
    matched: set = set()
    for rec in records:
        name = rec.get("metric")
        if not name or name not in history:
            continue
        matched.add(name)
        frozen_entry = history[name][-1]     # the newest frozen round wins
        frozen = frozen_entry["record"]
        # Generic skip regression: a metric that used to produce values
        # and now OOM-skips is always structural.
        if "skipped" in rec and "skipped" not in frozen:
            findings.append({
                "metric": name, "field": "skipped", "kind": "skip",
                "severity": "structural",
                "reason": f"previously-working metric now skipped: "
                          f"{rec['skipped']}",
                "fresh": rec.get("skipped"), "frozen": None,
                "source": frozen_entry["source"]})
            continue
        for check in _specs_for(name):
            reason = _check_one(check, rec, frozen)
            if reason is not None:
                findings.append({
                    "metric": name, "field": check.field,
                    "kind": check.kind,
                    "severity": ("structural" if check.structural
                                 else "wall"),
                    "reason": reason,
                    "fresh": _get(rec, check.field),
                    "frozen": _get(frozen, check.field),
                    "source": frozen_entry["source"]})
    return findings, sorted(matched)
