"""Profiling harness: wall-clock timing with proper device fencing,
compile-vs-run split, and jax.profiler trace capture.

The reference's only instrumentation is an unrecorded tic/toc per K-S VFI
iteration (Krusell_Smith_VFI.m:144,196-198). This module gives the framework
a real measurement surface; bench.py is built on it.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Callable

import jax
import jax.numpy as jnp

__all__ = ["fence", "Timing", "time_fn", "trace"]


def fence(tree) -> None:
    """Wait until `tree`'s computation actually finished.

    Uses a scalar device->host transfer of the first array leaf:
    block_until_ready alone does not reliably fence on remote/experimental
    TPU transports (observed on the tunneled v5e in this image)."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "dtype")]
    if leaves:
        jnp.asarray(leaves[0]).ravel()[:1].block_until_ready()
        float(jnp.sum(leaves[0].ravel()[:1]))


@dataclasses.dataclass
class Timing:
    """Result of time_fn: first call (compile+run) vs steady-state run."""

    compile_and_first_run_s: float
    run_s: float                  # best of `reps` post-compile calls
    reps: int

    @property
    def compile_s(self) -> float:
        return max(0.0, self.compile_and_first_run_s - self.run_s)


def time_fn(fn: Callable, *args, reps: int = 3, **kwargs) -> Timing:
    """Time `fn(*args)` with fencing: one cold call, then `reps` hot calls."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    fence(out)
    cold = time.perf_counter() - t0

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        fence(out)
        best = min(best, time.perf_counter() - t0)
    return Timing(cold, best, reps)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler trace (TensorBoard/Perfetto readable) around a
    block: `with trace('/tmp/trace'): run()`."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
