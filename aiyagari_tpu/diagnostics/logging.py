"""Structured iteration telemetry: typed records with pluggable sinks.

The reference's observability is disp/fprintf progress lines (iteration
indices at Aiyagari_VFI.m:89,205, EGM distances at Aiyagari_EGM.m:109, K-S ALM
coefficients/R^2 at Krusell_Smith_VFI.m:287-289). Here the outer loops emit
per-iteration dict records through an `on_iteration` callback; this module
provides the standard sinks (stdout table, JSONL file, in-memory collector)
and a multiplexer.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path
from typing import Callable, Iterable, Optional

__all__ = ["ConsoleSink", "JSONLSink", "CollectSink", "multiplex"]


class ConsoleSink:
    """Prints one aligned line per record to a stream (default stdout) —
    the disp/fprintf analogue, but uniform across solvers."""

    def __init__(self, stream=None, prefix: str = ""):
        self.stream = stream or sys.stdout
        self.prefix = prefix

    def __call__(self, record: dict) -> None:
        parts = []
        for k, v in record.items():
            if isinstance(v, float):
                parts.append(f"{k}={v:.6g}")
            elif isinstance(v, list):
                parts.append(f"{k}=[{', '.join(f'{x:.4g}' if isinstance(x, float) else str(x) for x in v)}]")
            else:
                parts.append(f"{k}={v}")
        print(self.prefix + " ".join(parts), file=self.stream)


class JSONLSink:
    """Appends each record as one JSON line — machine-readable run logs,
    usable for resume diagnostics and benchmark post-processing."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._t0 = time.time()

    def __call__(self, record: dict) -> None:
        rec = {"wall_time": round(time.time() - self._t0, 4), **record}
        with self.path.open("a") as f:
            f.write(json.dumps(rec) + "\n")


class CollectSink:
    """Collects records in memory (for tests and notebook use)."""

    def __init__(self):
        self.records: list[dict] = []

    def __call__(self, record: dict) -> None:
        self.records.append(record)


def multiplex(*sinks: Optional[Callable]) -> Callable:
    """Combine several sinks into one on_iteration callback; Nones skipped."""
    active = [s for s in sinks if s is not None]

    def emit(record: dict) -> None:
        for s in active:
            s(record)

    return emit
