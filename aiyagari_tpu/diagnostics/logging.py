"""Structured iteration telemetry: typed records with pluggable sinks.

The reference's observability is disp/fprintf progress lines (iteration
indices at Aiyagari_VFI.m:89,205, EGM distances at Aiyagari_EGM.m:109, K-S ALM
coefficients/R^2 at Krusell_Smith_VFI.m:287-289). Here the outer loops emit
per-iteration dict records through an `on_iteration` callback; this module
provides the standard sinks (stdout table, JSONL file, in-memory collector)
and a multiplexer.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path
from typing import Callable, Optional

__all__ = ["ConsoleSink", "JSONLSink", "CollectSink", "coerce_record",
           "multiplex"]


def _coerce_scalar(v):
    """Collapse numpy/jnp 0-d scalars (and numpy scalar types) to plain
    Python numbers. Solver records routinely carry them — `sol.distance` is
    a 0-d device array, `np.max(...)` a numpy scalar — and they are NOT
    `isinstance(v, float)`: the console sink printed them as opaque
    `Array(1.2e-06, dtype=float64)` reprs and json.dumps raised TypeError.
    Anything non-scalar (strings, dicts, >=1-d arrays) passes through."""
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    # numpy scalar types and 0-d arrays (jax arrays quack the same way).
    ndim = getattr(v, "ndim", None)
    if ndim == 0 and hasattr(v, "item"):
        v = v.item()
        # numpy datetime/str 0-d items pass through unchanged.
        return v
    return v


def coerce_record(record: dict) -> dict:
    """Recursively coerce a record's array scalars to Python numbers so it
    prints readably and JSON-serializes; shared by every sink here and the
    run ledger (diagnostics/ledger.py). Lists/tuples/dicts recurse; other
    leaves pass through _coerce_scalar."""

    def walk(v):
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [walk(x) for x in v]
        return _coerce_scalar(v)

    return {k: walk(v) for k, v in record.items()}


class ConsoleSink:
    """Prints one aligned line per record to a stream (default stdout) —
    the disp/fprintf analogue, but uniform across solvers."""

    def __init__(self, stream=None, prefix: str = ""):
        self.stream = stream or sys.stdout
        self.prefix = prefix

    def __call__(self, record: dict) -> None:
        parts = []
        for k, v in coerce_record(record).items():
            if isinstance(v, float):
                parts.append(f"{k}={v:.6g}")
            elif isinstance(v, list):
                parts.append(f"{k}=[{', '.join(f'{x:.4g}' if isinstance(x, float) else str(x) for x in v)}]")
            else:
                parts.append(f"{k}={v}")
        print(self.prefix + " ".join(parts), file=self.stream)


def _json_default(v):
    """json.dumps fallback for leaves coerce_record left alone (e.g. 1-d
    arrays inside records): try the array tolist protocol, else repr —
    a log line must never crash the solve that emits it."""
    if hasattr(v, "tolist"):
        return v.tolist()
    return repr(v)


class JSONLSink:
    """Appends each record as one JSON line — machine-readable run logs,
    usable for resume diagnostics and benchmark post-processing."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._t0 = time.time()

    def __call__(self, record: dict) -> None:
        rec = {"wall_time": round(time.time() - self._t0, 4),
               **coerce_record(record)}
        with self.path.open("a") as f:
            f.write(json.dumps(rec, default=_json_default) + "\n")


class CollectSink:
    """Collects records in memory (for tests and notebook use)."""

    def __init__(self):
        self.records: list[dict] = []

    def __call__(self, record: dict) -> None:
        self.records.append(record)


def multiplex(*sinks: Optional[Callable]) -> Callable:
    """Combine several sinks into one on_iteration callback; Nones skipped."""
    active = [s for s in sinks if s is not None]

    def emit(record: dict) -> None:
        for s in active:
            s(record)

    return emit
