"""In-jit iteration telemetry: device-resident fixed points report progress
through host callbacks (SURVEY.md §5.5).

The solver loops live entirely on device (lax.while_loop), so the host
normally sees nothing until convergence — the opposite extreme of the
reference, which prints every sweep (Aiyagari_EGM.m:109,
Krusell_Smith_VFI.m:196-198). This module restores opt-in visibility without
giving up the device-resident design: solvers call device_progress() every
`progress_every` iterations, which jax.debug.callback routes to whatever
sinks are subscribed (the same sink objects as diagnostics.logging). Off by
default — callbacks serialize host<->device traffic, so benchmarks and
production runs pay nothing.
"""

from __future__ import annotations

from contextlib import contextmanager
from functools import partial
from typing import Callable

import jax

__all__ = ["subscribe", "capture_progress", "device_progress", "reset"]

_SINKS: list[Callable[[dict], None]] = []


def subscribe(sink: Callable[[dict], None]) -> Callable[[], None]:
    """Register a sink for in-jit progress records; returns an unsubscribe
    function. Records are dicts {"context", "iteration", "distance"}."""
    _SINKS.append(sink)

    def unsubscribe() -> None:
        try:
            _SINKS.remove(sink)
        except ValueError:
            pass

    return unsubscribe


def reset() -> None:
    """Drop every subscribed sink. _SINKS is module-global state shared
    across threads and test cases; an autouse fixture calling reset() makes
    a leaked subscription (a test that crashed before its unsubscribe, a
    capture_progress block interrupted mid-teardown) impossible to carry
    into the next test."""
    _SINKS.clear()


@contextmanager
def capture_progress(sink: Callable[[dict], None]):
    """Scope a sink subscription: records emitted by any jitted solver running
    inside the with-block are delivered to `sink`."""
    unsubscribe = subscribe(sink)
    try:
        yield sink
    finally:
        # debug.callback effects are asynchronous: drain in-flight records
        # before dropping the subscription, or trailing ones vanish. The
        # barrier itself can raise (a dead device, an interrupted runtime) —
        # the subscription must still be dropped, or the sink leaks into
        # every later solve in the process (test-isolation hazard).
        try:
            jax.effects_barrier()
        finally:
            unsubscribe()


def _deliver(context: str, iteration, distance) -> None:
    record = {
        "context": context,
        "iteration": int(iteration),
        "distance": float(distance),
    }
    for sink in list(_SINKS):
        sink(record)


def device_progress(context: str, iteration, distance, *, every: int) -> None:
    """Emit one progress record from inside a jitted loop body.

    `every` is static: 0 disables (the call traces to nothing, zero cost);
    otherwise a record is emitted on iterations where (iteration % every)==0.
    Callbacks are unordered (jax.debug.callback), so sinks must not assume
    strict monotone delivery across devices.
    """
    if not every:
        return

    def _emit(args):
        it, dist = args
        # context is static Python data: close over it rather than passing it
        # through the callback's (array-only) argument path.
        jax.debug.callback(partial(_deliver, context), it, dist)

    jax.lax.cond(
        iteration % every == 0,
        _emit,
        lambda args: None,
        (iteration, distance),
    )
