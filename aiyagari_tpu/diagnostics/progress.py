"""In-jit iteration telemetry: device-resident fixed points report progress
through host callbacks (SURVEY.md §5.5).

The solver loops live entirely on device (lax.while_loop), so the host
normally sees nothing until convergence — the opposite extreme of the
reference, which prints every sweep (Aiyagari_EGM.m:109,
Krusell_Smith_VFI.m:196-198). This module restores opt-in visibility without
giving up the device-resident design: solvers call device_progress() every
`progress_every` iterations, which jax.debug.callback routes to whatever
sinks are subscribed (the same sink objects as diagnostics.logging). Off by
default — callbacks serialize host<->device traffic, so benchmarks and
production runs pay nothing.

Heartbeats (the pod observatory's live layer, docs/USAGE.md "Pod
observatory"): with `configure_heartbeat(stride)` armed, every stride-th
delivered progress record ALSO lands on the ACTIVE run ledger
(diagnostics/ledger.py) as a `heartbeat` event — host-stamped by the
ledger, carrying the residual's dtype (the live stage-dtype signal for the
mixed-precision ladder) — and lockstep sweep round loops publish their
per-scenario state through `sweep_heartbeat`. `python -m aiyagari_tpu
watch` tails and renders them. Heartbeats are PURE host-side fan-out: the
stride is never traced, so heartbeat-off (and heartbeat-on) programs are
bit-identical to the historical ones — only `progress_every` shapes the
compiled program, exactly as before (jaxpr-pinned by
tests/test_pod_observatory.py).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from functools import partial
from typing import Callable

import jax

__all__ = [
    "subscribe",
    "capture_progress",
    "configure_heartbeat",
    "device_progress",
    "heartbeat_stride",
    "reset",
    "sweep_heartbeat",
]

_SINKS: list[Callable[[dict], None]] = []

# Heartbeat state: stride 0 = off (the default — no ledger interaction at
# all); stride N emits every Nth delivered record per context. Host-side
# only, never traced. The counter map is written from jax debug-callback
# threads, so its read-modify-write takes a lock (concurrent deliveries
# for one context would otherwise drop counts and drift the stride).
_HEARTBEAT = {"stride": 0}
_HEARTBEAT_COUNTS: dict = {}
_HEARTBEAT_LOCK = threading.Lock()


def subscribe(sink: Callable[[dict], None]) -> Callable[[], None]:
    """Register a sink for in-jit progress records; returns an unsubscribe
    function. Records are dicts {"context", "iteration", "distance",
    "dtype"}."""
    _SINKS.append(sink)

    def unsubscribe() -> None:
        try:
            _SINKS.remove(sink)
        except ValueError:
            pass

    return unsubscribe


def configure_heartbeat(stride: int) -> None:
    """Arm (or disarm) ledger heartbeats: every `stride`-th delivered
    progress record per context — and every `stride`-th lockstep sweep
    round (sweep_heartbeat) — is appended to the ACTIVE ledger as a
    `heartbeat` event. 0 (the default) disables; the delivery counters
    reset on every call so a re-armed watch starts on the next record."""
    stride = int(stride)
    if stride < 0:
        raise ValueError(f"heartbeat stride must be >= 0, got {stride}")
    _HEARTBEAT["stride"] = stride
    _HEARTBEAT_COUNTS.clear()


def heartbeat_stride() -> int:
    return _HEARTBEAT["stride"]


def reset() -> None:
    """Drop every subscribed sink and disarm heartbeats. _SINKS (and the
    heartbeat stride) are module-global state shared across threads and
    test cases; an autouse fixture calling reset() makes a leaked
    subscription (a test that crashed before its unsubscribe, a
    capture_progress block interrupted mid-teardown) impossible to carry
    into the next test."""
    _SINKS.clear()
    _HEARTBEAT["stride"] = 0
    _HEARTBEAT_COUNTS.clear()


@contextmanager
def capture_progress(sink: Callable[[dict], None]):
    """Scope a sink subscription: records emitted by any jitted solver running
    inside the with-block are delivered to `sink`."""
    unsubscribe = subscribe(sink)
    try:
        yield sink
    finally:
        # debug.callback effects are asynchronous: drain in-flight records
        # before dropping the subscription, or trailing ones vanish. The
        # barrier itself can raise (a dead device, an interrupted runtime) —
        # the subscription must still be dropped, or the sink leaks into
        # every later solve in the process (test-isolation hazard).
        try:
            jax.effects_barrier()
        finally:
            unsubscribe()


def _maybe_heartbeat(context: str, record: dict) -> None:
    """Land every stride-th record per context on the active ledger. A
    no-op (zero ledger interaction) when heartbeats are off or no ledger
    is active — and ALWAYS host-side, so the compiled programs cannot
    depend on it."""
    stride = _HEARTBEAT["stride"]
    if not stride:
        return
    from aiyagari_tpu.diagnostics import ledger

    if ledger.active_ledger() is None:
        return
    with _HEARTBEAT_LOCK:
        n = _HEARTBEAT_COUNTS.get(context, 0)
        _HEARTBEAT_COUNTS[context] = n + 1
    if n % stride == 0:
        ledger.emit("heartbeat", **record)


def sweep_heartbeat(context: str, *, round_index: int, **fields) -> None:
    """Host-side heartbeat from a lockstep sweep's round loop
    (equilibrium/batched.py, transition/mit.py): publishes the round's
    per-scenario state ({"gap": [...], "converged": [...], ...}) as a
    `heartbeat` event on the active ledger every `stride` rounds. The
    round loops are host code, so this never touches a traced program; off
    (the default) it is one dict lookup per round."""
    stride = _HEARTBEAT["stride"]
    if not stride or round_index % stride:
        return
    from aiyagari_tpu.diagnostics import ledger

    if ledger.active_ledger() is None:
        return
    ledger.emit("heartbeat", context=context, round=int(round_index),
                **fields)


def _deliver(context: str, iteration, distance) -> None:
    import numpy as np

    it = np.asarray(iteration)
    dist = np.asarray(distance)
    record = {
        "context": context,
        # vmapped callers deliver batched iterations/distances (jax batches
        # the callback's operands); per-lane values ride as lists so a
        # sweep's heartbeat renders per-scenario rows.
        "iteration": int(it) if it.ndim == 0 else it.tolist(),
        "distance": (float(dist) if dist.ndim == 0
                     else [float(x) for x in np.ravel(dist)]),
        # The residual's on-device dtype IS the live stage-dtype signal:
        # a mixed-ladder solve heartbeats float32 until the switch fires.
        "dtype": str(dist.dtype),
    }
    for sink in list(_SINKS):
        sink(record)
    _maybe_heartbeat(context, record)


# The AIYA103 whitelist contract (analysis/rules.CALLBACK_TAG_ATTR): the
# progress callback is a sanctioned in-loop host callback — the dunder is
# set literally so this module needs no analysis import.
_deliver.__aiyagari_callback_tag__ = "progress"


def device_progress(context: str, iteration, distance, *, every: int) -> None:
    """Emit one progress record from inside a jitted loop body.

    `every` is static: 0 disables (the call traces to nothing, zero cost);
    otherwise a record is emitted on iterations where (iteration % every)==0.
    Callbacks are unordered (jax.debug.callback), so sinks must not assume
    strict monotone delivery across devices.
    """
    if not every:
        return

    def _emit(args):
        it, dist = args
        # context is static Python data: close over it rather than passing it
        # through the callback's (array-only) argument path.
        jax.debug.callback(partial(_deliver, context), it, dist)

    jax.lax.cond(
        iteration % every == 0,
        _emit,
        lambda args: None,
        (iteration, distance),
    )
