"""Nested wall-clock span tracing with device-profiler annotations and a
compile-vs-run split.

`diagnostics/profiler.py` times ONE function with proper fencing; this
module adds structure: named spans that nest (`with span("ge_bisect"):`),
land in the device profiler's timeline as `jax.profiler.TraceAnnotation`s
(so a TensorBoard/Perfetto capture shows the host phases next to the XLA
ops), and are collected as plain dicts the run ledger stores. No global
mutable trace unless you open one: spans outside a `collect_spans()` scope
still annotate the profiler but are otherwise dropped.

    with collect_spans() as spans:
        with span("anchor"):
            ...
        with span("newton", round=3):
            ...
    ledger.span(spans[0])

`timed(name, fn, *args)` wraps profiler.time_fn to record the
compile-vs-run split as a span — the same cold/hot semantics bench.py
reports, available to any instrumented call site.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, List

__all__ = ["collect_spans", "span", "timed"]

_tls = threading.local()

# Run-id-keyed collectors (process-global, lock-guarded): spans closed on
# ANY thread whose active ledger carries that run id are delivered here —
# without this, concurrent runs (a serve layer's worker threads, a rescue
# ladder re-solving on a helper thread) pooled their wall-clock into
# whichever collector happened to be thread-local, and a merged multi-host
# report attributed one run's spans to another. The thread-local sinks
# below keep their historical semantics for run-less collection.
_run_lock = threading.Lock()
_run_sinks: dict = {}


def _stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def _sinks() -> list:
    if not hasattr(_tls, "sinks"):
        _tls.sinks = []
    return _tls.sinks


def _active_run_id():
    try:
        from aiyagari_tpu.diagnostics.ledger import active_ledger

        led = active_ledger()
    except Exception:
        return None
    return None if led is None else led.run_id


@contextlib.contextmanager
def collect_spans(run_id: str = None) -> Iterator[List[dict]]:
    """Scope a span collector: every TOP-LEVEL span closed inside the block
    is appended to the yielded list (children ride inside their parent's
    "children" field). Nested collectors each receive the spans closed in
    their scope. Exception-safe: the collector is removed even when the
    block raises.

    `run_id` keys the collector to one run (thread-safe): spans closed on
    any thread whose ACTIVE ledger (diagnostics/ledger.py) carries that
    run id are delivered here too — so a run's wall-clock is attributed to
    the run, not to whichever thread happened to host the collector. Each
    such span record is stamped with its "run_id"."""
    out: List[dict] = []
    _sinks().append(out)
    if run_id is not None:
        with _run_lock:
            _run_sinks.setdefault(run_id, []).append(out)
    try:
        yield out
    finally:
        _sinks().remove(out)
        if run_id is not None:
            with _run_lock:
                lst = _run_sinks.get(run_id, [])
                for i in range(len(lst) - 1, -1, -1):
                    if lst[i] is out:
                        del lst[i]
                        break
                if not lst:
                    _run_sinks.pop(run_id, None)


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[dict]:
    """One named wall-clock span. Nesting is structural: a span opened
    inside another becomes a child record. The block also runs under
    jax.profiler.TraceAnnotation(name), so device traces carry the same
    names (annotation failures — e.g. no profiler backend — are never
    allowed to break the solve)."""
    rec = {"name": name, **attrs, "children": []}
    stack = _stack()
    parent = stack[-1] if stack else None
    stack.append(rec)
    ann = None
    try:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    except Exception:
        ann = None
    t0 = time.perf_counter()
    try:
        yield rec
    finally:
        rec["seconds"] = round(time.perf_counter() - t0, 6)
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        stack.pop()
        if not rec["children"]:
            del rec["children"]
        if parent is not None:
            parent.setdefault("children", []).append(rec)
        else:
            targets = list(_sinks())
            run_id = _active_run_id()
            if run_id is not None:
                rec.setdefault("run_id", run_id)
                with _run_lock:
                    keyed = list(_run_sinks.get(run_id, ()))
                for sink in keyed:
                    # A collector registered BOTH thread-locally and under
                    # the run id (the dispatch _observe scope) receives the
                    # span once.
                    if not any(sink is t for t in targets):
                        targets.append(sink)
            for sink in targets:
                sink.append(rec)


def timed(name: str, fn, *args, reps: int = 1, **kwargs):
    """Run `fn(*args, **kwargs)` under a span that records the
    compile-vs-run split (profiler.time_fn semantics: one fenced cold call,
    `reps` fenced hot calls, compile = cold - best hot). Returns
    (cold result, span_record). reps=0 skips the hot calls (the span then
    carries only the cold wall) for call sites that cannot afford a
    re-execution."""
    from aiyagari_tpu.diagnostics.profiler import fence

    with span(name) as rec:
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        fence(out)
        cold = time.perf_counter() - t0
        best = None
        for _ in range(max(reps, 0)):
            t0 = time.perf_counter()
            fence(fn(*args, **kwargs))
            best = min(best or float("inf"), time.perf_counter() - t0)
    rec["compile_and_first_run_s"] = round(cold, 6)
    if best is not None:
        rec["run_s"] = round(best, 6)
        rec["compile_s"] = round(max(0.0, cold - best), 6)
    return out, rec
