"""Structured non-convergence reporting (SURVEY.md §5.3).

The reference's failure handling is a printed warning after which the script
continues with whatever it last computed (Aiyagari_EGM.m:112-116,216-220); its
max-iteration caps (Aiyagari_VFI.m:49, Krusell_Smith_VFI.m:12) guard silently.
Here the guard carries data: ConvergenceError records where the iteration
stopped and how far from tolerance it was, so callers (and resumed runs) can
act on it. The default policy stays reference-faithful ("warn" and return the
last iterate); "raise" upgrades the guard to a hard failure for CI and
unattended runs.
"""

from __future__ import annotations

import warnings

__all__ = ["ConvergenceError", "ConvergenceWarning", "enforce_convergence"]

_POLICIES = ("ignore", "warn", "raise")


class ConvergenceWarning(UserWarning):
    """A fixed point hit its iteration cap; the returned result is the last
    iterate, not a converged one."""


class ConvergenceError(RuntimeError):
    """A fixed point hit its iteration cap under policy='raise'.

    Attributes carry the loop's final state so the failure is diagnosable
    and resumable without re-running: `context` names the loop, `iterations`
    how many steps ran, `distance` the last convergence measure against
    `tol`, `detail` any loop-specific extras (e.g. the r-bracket or the
    ALM coefficient step), and `telemetry` the loop's final SolveTelemetry
    flight record (diagnostics/telemetry.py) when the solve carried one —
    the residual trajectory that says WHY the cap was hit (stall vs slow
    geometric decay vs oscillation), attached so policy='raise' failures
    ship their own diagnosis.
    """

    def __init__(self, context: str, *, iterations: int, distance: float,
                 tol: float, detail: dict | None = None, telemetry=None):
        self.context = context
        self.iterations = int(iterations)
        self.distance = float(distance)
        self.tol = float(tol)
        self.detail = dict(detail or {})
        self.telemetry = telemetry
        extra = f" ({', '.join(f'{k}={v}' for k, v in self.detail.items())})" if self.detail else ""
        super().__init__(
            f"{context}: no convergence after {self.iterations} iterations; "
            f"last distance {self.distance:.3e} vs tol {self.tol:.1e}{extra}"
        )


def enforce_convergence(converged: bool, policy: str, context: str, *,
                        iterations: int, distance: float, tol: float,
                        detail: dict | None = None, telemetry=None) -> None:
    """Apply a non-convergence policy: no-op when converged or
    policy='ignore'; emit ConvergenceWarning for 'warn' (the reference's
    behavior, made typed); raise ConvergenceError for 'raise', carrying
    `telemetry` (the loop's flight record, when one exists) on the error."""
    if policy not in _POLICIES:
        raise ValueError(f"unknown on_nonconvergence policy {policy!r}; expected one of {_POLICIES}")
    if converged or policy == "ignore":
        return
    if policy == "raise":
        raise ConvergenceError(context, iterations=iterations, distance=distance,
                               tol=tol, detail=detail, telemetry=telemetry)
    warnings.warn(
        str(ConvergenceError(context, iterations=iterations, distance=distance,
                             tol=tol, detail=detail)),
        ConvergenceWarning,
        stacklevel=3,
    )
