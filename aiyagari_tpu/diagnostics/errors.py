"""Structured non-convergence reporting (SURVEY.md §5.3).

The reference's failure handling is a printed warning after which the script
continues with whatever it last computed (Aiyagari_EGM.m:112-116,216-220); its
max-iteration caps (Aiyagari_VFI.m:49, Krusell_Smith_VFI.m:12) guard silently.
Here the guard carries data: ConvergenceError records where the iteration
stopped and how far from tolerance it was, so callers (and resumed runs) can
act on it. The default policy stays reference-faithful ("warn" and return the
last iterate); "raise" upgrades the guard to a hard failure for CI and
unattended runs.

Non-finite distances are their own verdict: a NaN distance fails `< tol`
silently, so before this fix a NaN-poisoned solve under policy "warn" looked
identical to an ordinary iteration-cap miss — and under "ignore" it was
entirely silent. A non-finite distance now always reports as verdict "nan"
and is ALWAYS loud: it warns even under "ignore" and even when the caller's
`converged` flag claims success (a converged flag computed from a criterion
the NaN also slipped through), and raises under "raise". Zero silent NaN
results is the resilience contract (ISSUE 10).
"""

from __future__ import annotations

import math
import warnings

__all__ = ["ConvergenceError", "ConvergenceWarning", "enforce_convergence"]

_POLICIES = ("ignore", "warn", "raise")


class ConvergenceWarning(UserWarning):
    """A fixed point hit its iteration cap (or reported a non-finite
    distance); the returned result is the last iterate, not a converged
    one."""


class ConvergenceError(RuntimeError):
    """A fixed point failed under policy='raise' (or exhausted a rescue
    ladder).

    Attributes carry the loop's final state so the failure is diagnosable
    and resumable without re-running: `context` names the loop, `iterations`
    how many steps ran, `distance` the last convergence measure against
    `tol`, `verdict` the structured failure class ("max_iter" for an
    ordinary cap miss, "nan" for a non-finite distance, or a sentinel
    verdict like "stall"/"explode"/"escape" when the caller supplies one),
    `detail` any loop-specific extras (e.g. the r-bracket or the ALM
    coefficient step), `telemetry` the loop's final SolveTelemetry flight
    record (diagnostics/telemetry.py) when the solve carried one — the
    residual trajectory that says WHY the cap was hit — and `attempts` the
    full rescue-ladder attempt history (a list of
    diagnostics.rescue.RescueAttempt) when a rescue ladder exhausted
    itself raising this error.
    """

    def __init__(self, context: str, *, iterations: int, distance: float,
                 tol: float, detail: dict | None = None, telemetry=None,
                 verdict: str | None = None, attempts=None):
        self.context = context
        self.iterations = int(iterations)
        self.distance = float(distance)
        self.tol = float(tol)
        self.detail = dict(detail or {})
        self.telemetry = telemetry
        self.attempts = list(attempts) if attempts is not None else []
        if verdict is None:
            verdict = "nan" if not math.isfinite(self.distance) else "max_iter"
        self.verdict = verdict
        extra = f" ({', '.join(f'{k}={v}' for k, v in self.detail.items())})" if self.detail else ""
        stages = (f"; rescue ladder exhausted after {len(self.attempts)} "
                  f"attempt(s): {[a.stage for a in self.attempts]}"
                  if self.attempts else "")
        super().__init__(
            f"{context}: no convergence after {self.iterations} iterations "
            f"[verdict={self.verdict}]; "
            f"last distance {self.distance:.3e} vs tol {self.tol:.1e}{extra}"
            f"{stages}"
        )


def enforce_convergence(converged: bool, policy: str, context: str, *,
                        iterations: int, distance: float, tol: float,
                        detail: dict | None = None, telemetry=None,
                        verdict: str | None = None) -> None:
    """Apply a non-convergence policy: no-op when converged or
    policy='ignore'; emit ConvergenceWarning for 'warn' (the reference's
    behavior, made typed); raise ConvergenceError for 'raise', carrying
    `telemetry` (the loop's flight record, when one exists) on the error.

    Non-finite `distance` is the explicit "nan" verdict and is ALWAYS loud
    (module docstring): it overrides a True `converged` flag, warns under
    'ignore' and 'warn', and raises under 'raise'. `verdict` lets outer
    loops carrying a sentinel verdict ("stall"/"explode"/"escape") name
    the failure class; it defaults from the distance."""
    if policy not in _POLICIES:
        raise ValueError(f"unknown on_nonconvergence policy {policy!r}; expected one of {_POLICIES}")
    nonfinite = not math.isfinite(float(distance))
    if nonfinite and verdict is None:
        verdict = "nan"
    if (converged and not nonfinite) or (policy == "ignore" and not nonfinite):
        return
    if policy == "raise":
        raise ConvergenceError(context, iterations=iterations, distance=distance,
                               tol=tol, detail=detail, telemetry=telemetry,
                               verdict=verdict)
    warnings.warn(
        str(ConvergenceError(context, iterations=iterations, distance=distance,
                             tol=tol, detail=detail, verdict=verdict)),
        ConvergenceWarning,
        stacklevel=3,
    )
