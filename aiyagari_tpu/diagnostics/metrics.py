"""Process-wide metrics registry: counters, gauges, histograms, with
Prometheus-text and JSON exporters.

The persistent-solve-service item on the ROADMAP needs a scrape surface —
a long-lived server cannot re-derive "how many push-forward fallbacks fired
since boot" from per-solve results. This registry is that surface: tiny,
dependency-free, thread-safe, and shaped so the future serve layer exposes
`render_prometheus()` at /metrics verbatim. Solver-internal degradation
events (ops/pushforward.py's fallback counter) land here through async
`jax.debug.callback`s, so the hot device programs never block on it.

Deliberately NOT a client-library clone: no label cardinality policing, no
metric families beyond the three everything here needs. Labels are plain
kwargs; a (name, sorted labels) pair is one time series.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "gauge",
    "histogram",
    "registry",
    "render_json",
    "render_prometheus",
    "reset",
]

# Histogram defaults tuned for solver residuals/walls: log-spaced, wide.
_DEFAULT_BUCKETS = (1e-10, 1e-8, 1e-6, 1e-4, 1e-2, 1.0, 100.0)

_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


_HOST_LABELS_CACHE: Optional[dict] = None


def _host_labels() -> dict:
    """The pod host label: {"host": "<process_index>"} on a multi-process
    JAX runtime, {} single-process — so one host's scrape (or a merged
    dump) attributes every series to the process that produced it, while
    single-host exposition stays byte-identical to the historical output.
    Consulted only when jax is already imported (backend-init-free:
    parallel/distributed.peek_process_topology reads the distributed
    global state) — the registry stays usable by jax-free unit code. The
    label is CACHED once multi-process is observed (the topology never
    changes after jax.distributed.initialize), keeping the per-call cost
    of every counter/gauge off the re-resolve path; series touched before
    the distributed init keep the unlabeled identity, same as ledger
    events stamped (0, 1) before it."""
    global _HOST_LABELS_CACHE
    if _HOST_LABELS_CACHE is not None:
        return _HOST_LABELS_CACHE
    if "jax" not in sys.modules:
        return {}
    try:
        from aiyagari_tpu.parallel.distributed import peek_process_topology

        pid, count = peek_process_topology()
    except Exception:
        return {}
    if count > 1:
        _HOST_LABELS_CACHE = {"host": str(pid)}
        return _HOST_LABELS_CACHE
    return {}


def _key(name: str, labels: dict) -> _Key:
    labels = {**_host_labels(), **labels}
    return name, tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotone event count."""

    def __init__(self, reg: "MetricsRegistry", key: _Key):
        self._reg, self._key = reg, key

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counters only go up; got inc({n})")
        with self._reg._lock:
            self._reg._counters[self._key] = (
                self._reg._counters.get(self._key, 0.0) + float(n))

    @property
    def value(self) -> float:
        with self._reg._lock:
            return self._reg._counters.get(self._key, 0.0)


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self, reg: "MetricsRegistry", key: _Key):
        self._reg, self._key = reg, key

    def set(self, v: float) -> None:
        with self._reg._lock:
            self._reg._gauges[self._key] = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._reg._lock:
            return self._reg._gauges.get(self._key)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket counts
    observations <= its upper bound; +Inf is implicit via `count`)."""

    def __init__(self, reg: "MetricsRegistry", key: _Key, buckets):
        self._reg, self._key = reg, key
        self._buckets = tuple(sorted(float(b) for b in buckets))
        with reg._lock:
            reg._histograms.setdefault(
                key, {"buckets": self._buckets,
                      "counts": [0] * len(self._buckets),
                      "count": 0, "sum": 0.0})

    def observe(self, v: float) -> None:
        v = float(v)
        with self._reg._lock:
            # setdefault, not indexing: a handle held across registry.reset()
            # (module-level caching is the intended usage pattern) must
            # re-create its series, exactly as Counter/Gauge implicitly do.
            h = self._reg._histograms.setdefault(
                self._key, {"buckets": self._buckets,
                            "counts": [0] * len(self._buckets),
                            "count": 0, "sum": 0.0})
            for i, b in enumerate(h["buckets"]):
                if v <= b:
                    h["counts"][i] += 1
            h["count"] += 1
            h["sum"] += v


class MetricsRegistry:
    """One process's metric store. The module-level `registry` is the
    default every helper below uses; tests may build private ones."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[_Key, float] = {}
        self._gauges: Dict[_Key, float] = {}
        self._histograms: Dict[_Key, dict] = {}
        self._started = time.time()

    def counter(self, name: str, **labels) -> Counter:
        return Counter(self, _key(name, labels))

    def gauge(self, name: str, **labels) -> Gauge:
        return Gauge(self, _key(name, labels))

    def histogram(self, name: str, buckets=_DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return Histogram(self, _key(name, labels), buckets)

    def reset(self) -> None:
        """Drop every series — autouse-fixture friendly (the registry is
        process-global, exactly the state tests must not leak)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    # -- exporters ---------------------------------------------------------

    def render_json(self) -> dict:
        def unkey(store):
            return [
                {"name": name, "labels": dict(labels), "value": v}
                for (name, labels), v in sorted(store.items())
            ]

        with self._lock:
            return {
                "counters": unkey(self._counters),
                "gauges": unkey(self._gauges),
                "histograms": [
                    {"name": name, "labels": dict(labels),
                     "buckets": list(h["buckets"]), "counts": list(h["counts"]),
                     "count": h["count"], "sum": h["sum"]}
                    for (name, labels), h in sorted(self._histograms.items())
                ],
                "uptime_seconds": round(time.time() - self._started, 3),
            }

    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4 — the string a serve
        layer returns from /metrics."""

        def esc(v):
            # Label-value escaping per the text format: backslash first
            # (or it would re-escape the other two), then quote and
            # newline — a route name / path landing in a label must not
            # produce unparseable exposition.
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        def fmt_labels(labels, extra=()):
            items = list(labels) + list(extra)
            if not items:
                return ""
            return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in items) + "}"

        lines = []
        # ONE "# TYPE" line per metric NAME, not per label-set series — the
        # Prometheus text parser rejects a repeated TYPE for the same name,
        # which is exactly what multi-route fallback counters produce.
        typed: set = set()

        def typ(name, kind):
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {name} {kind}")

        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                typ(name, "counter")
                lines.append(f"{name}{fmt_labels(labels)} {v:g}")
            for (name, labels), v in sorted(self._gauges.items()):
                typ(name, "gauge")
                lines.append(f"{name}{fmt_labels(labels)} {v:g}")
            for (name, labels), h in sorted(self._histograms.items()):
                typ(name, "histogram")
                for b, c in zip(h["buckets"], h["counts"]):
                    lines.append(
                        f"{name}_bucket{fmt_labels(labels, [('le', f'{b:g}')])} {c}")
                lines.append(
                    f"{name}_bucket{fmt_labels(labels, [('le', '+Inf')])} {h['count']}")
                lines.append(f"{name}_sum{fmt_labels(labels)} {h['sum']:g}")
                lines.append(f"{name}_count{fmt_labels(labels)} {h['count']}")
        return "\n".join(lines) + ("\n" if lines else "")


registry = MetricsRegistry()


def counter(name: str, **labels) -> Counter:
    return registry.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return registry.gauge(name, **labels)


def histogram(name: str, buckets=_DEFAULT_BUCKETS, **labels) -> Histogram:
    return registry.histogram(name, buckets, **labels)


def render_prometheus() -> str:
    return registry.render_prometheus()


def render_json() -> dict:
    return registry.render_json()


def reset() -> None:
    registry.reset()


def dump_json(path) -> None:
    """Write the JSON exposition to a file (bench.py's per-run snapshot)."""
    with open(path, "w") as f:
        json.dump(registry.render_json(), f, indent=2)
        f.write("\n")
