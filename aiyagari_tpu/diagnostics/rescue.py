"""Host-side rescue ladder: bounded escalation for failed solves.

The resilience contract (ISSUE 10) is that a solve either converges or
fails LOUDLY with a structured verdict — but for the ROADMAP's
million-economy calibration sweeps "fails loudly" is not enough: one
pathological calibration must not cost its whole batch, and most
pathologies are ROUTE pathologies (an acceleration history poisoned by a
kinked operator, a windowed inversion whose knot density escapes, an f32
hot stage whose noise floor sits above the target) that a more
conservative configuration solves outright. The rescue ladder encodes that
escalation once, at the dispatch boundary:

    base -> plain -> safe -> float64 -> patient

Each stage rebuilds the solve from the BASE configuration with
progressively more machinery disabled (config.RescueConfig names the
semantics); the first converged attempt returns, and exhaustion raises a
ConvergenceError carrying the full attempt history. Every attempt lands on
the observability surface: a ledger "rescue" event and an
`aiyagari_rescue_attempts_total{stage=,outcome=}` metrics increment —
a fleet operator reads the rescue rate off /metrics, not out of logs.

The driver is deliberately solve-shape-agnostic: `run_rescue` takes an
`attempt(solver, backend, outer)` callable (dispatch closes it over the
real entry point with policy="raise", so failures arrive as exceptions)
plus the three config objects each stage transforms. Injected faults
(diagnostics/faults.py) are cleared on every rescue stage except
`fail_stage`, which targets this driver itself — the CI battery's way of
exercising multi-stage escalation deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from aiyagari_tpu.config import (
    BackendConfig,
    EquilibriumConfig,
    RescueConfig,
    SolverConfig,
    TransitionConfig,
)
from aiyagari_tpu.diagnostics.errors import ConvergenceError
from aiyagari_tpu.diagnostics.faults import stage_fails

__all__ = [
    "RescueAttempt",
    "RescueConfig",
    "RESCUE_STAGES",
    "apply_stage",
    "run_rescue",
]

# Stage vocabulary (order is the escalation; RescueConfig.stages selects).
RESCUE_STAGES = ("plain", "safe", "float64", "patient")


@dataclasses.dataclass
class RescueAttempt:
    """One ladder attempt's record — what the ledger "rescue" event stores
    and what ConvergenceError.attempts carries on exhaustion."""

    stage: str
    converged: bool
    verdict: str = "ok"          # "ok" | error verdict ("nan"/"max_iter"/...)
    error: Optional[str] = None  # the failed attempt's exception message
    distance: float = float("nan")
    iterations: int = 0
    seconds: float = 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _check_stages(rescue: RescueConfig) -> tuple:
    unknown = [s for s in rescue.stages if s not in RESCUE_STAGES]
    if unknown:
        raise ValueError(
            f"unknown rescue stage(s) {unknown}; known stages (escalation "
            f"order): {RESCUE_STAGES}")
    return tuple(rescue.stages)


def apply_stage(stage: str, solver: SolverConfig, backend: BackendConfig,
                outer):
    """The (solver, backend, outer) triple one ladder stage runs with,
    built from the BASE configs (stages are standalone escalations, not
    cumulative state — semantics on RescueConfig's docstring). `outer` is
    the outer-loop config the solve family uses (EquilibriumConfig or
    TransitionConfig); "base" returns the inputs untouched."""
    if stage == "base":
        return solver, backend, outer
    # Every rescue stage re-runs the operator fresh: injected faults model
    # a route/data pathology the escalation replaces (FaultPlan docstring),
    # so they are cleared here — fail_stage excepted, it targets run_rescue.
    solver = dataclasses.replace(solver, faults=None, accel=None,
                                 use_pallas=False, egm_kernel="xla")
    if stage == "plain":
        return solver, backend, outer
    solver = dataclasses.replace(solver, pushforward="scatter")
    trans = isinstance(outer, TransitionConfig)
    if trans and outer.method != "damped":
        outer = dataclasses.replace(outer, method="damped")
    if stage == "safe":
        return solver, backend, outer
    solver = dataclasses.replace(solver, ladder=None)
    backend = dataclasses.replace(backend, dtype="float64")
    if stage == "float64":
        return solver, backend, outer
    # "patient": doubled caps, and for transitions halved damping — the
    # last-resort slow-but-steady configuration.
    solver = dataclasses.replace(solver, max_iter=2 * solver.max_iter)
    outer = dataclasses.replace(
        outer, max_iter=2 * outer.max_iter,
        **({"damping": 0.5 * outer.damping} if trans else {}))
    return solver, backend, outer


def _record(ledger, attempt: RescueAttempt, context: str) -> None:
    from aiyagari_tpu.diagnostics import metrics

    metrics.counter(
        "aiyagari_rescue_attempts_total", stage=attempt.stage,
        outcome="converged" if attempt.converged else "failed").inc()
    if ledger is not None:
        ledger.event("rescue", context=context, **attempt.to_json())


def run_rescue(attempt_fn: Callable, *, rescue: RescueConfig,
               solver: SolverConfig, backend: BackendConfig, outer,
               context: str, tol: float, ledger=None):
    """Drive one solve through the ladder: the base attempt, then each
    configured rescue stage, stopping at the first success.

    `attempt_fn(solver, backend, outer)` must RAISE on failure
    (ConvergenceError / FloatingPointError — dispatch runs the inner solve
    with policy="raise") and return the converged result otherwise. The
    returned result gains a `rescue_attempts` attribute (the full history,
    successful final attempt included). Exhaustion raises a
    ConvergenceError whose `attempts` carry the history and whose verdict
    is the LAST attempt's."""
    stages = ("base",) + _check_stages(rescue)
    attempts: List[RescueAttempt] = []
    faults = solver.faults
    last: Optional[ConvergenceError] = None
    for stage in stages:
        s2, b2, o2 = apply_stage(stage, solver, backend, outer)
        t0 = time.perf_counter()
        if stage_fails(faults, stage):
            att = RescueAttempt(stage=stage, converged=False,
                                verdict="injected",
                                error="forced failure (FaultPlan.fail_stage)",
                                seconds=time.perf_counter() - t0)
            attempts.append(att)
            _record(ledger, att, context)
            continue
        try:
            result = attempt_fn(s2, b2, o2)
        except ConvergenceError as e:
            att = RescueAttempt(stage=stage, converged=False,
                                verdict=e.verdict, error=str(e),
                                distance=e.distance, iterations=e.iterations,
                                seconds=time.perf_counter() - t0)
            attempts.append(att)
            _record(ledger, att, context)
            last = e
            continue
        except FloatingPointError as e:
            # The transition path evaluator's divergence signal: no distance
            # to report beyond "non-finite".
            att = RescueAttempt(stage=stage, converged=False, verdict="nan",
                                error=str(e),
                                seconds=time.perf_counter() - t0)
            attempts.append(att)
            _record(ledger, att, context)
            last = None
            continue
        att = RescueAttempt(
            stage=stage, converged=True,
            iterations=int(getattr(result, "iterations",
                                   getattr(result, "rounds", 0)) or 0),
            seconds=time.perf_counter() - t0)
        attempts.append(att)
        _record(ledger, att, context)
        result.rescue_attempts = attempts
        return result
    failed = [a for a in attempts if not a.converged]
    raise ConvergenceError(
        context,
        iterations=(last.iterations if last is not None else 0),
        distance=(last.distance if last is not None else float("nan")),
        tol=tol,
        detail={"stages_tried": [a.stage for a in attempts]},
        telemetry=(last.telemetry if last is not None else None),
        verdict=(failed[-1].verdict if failed else "max_iter"),
        attempts=attempts,
    )
