"""Run ledger: an append-only JSONL event store keyed by a run id — every
solve's traceable, exportable flight record.

The ROADMAP's calibration item will fit millions of economies whose
failures must be diagnosable WITHOUT re-running; the serve item needs a
durable record of what each request did. The ledger is the storage half of
that story: one JSONL file per run (or shared across runs — events carry
their run id), each line one event:

    {"run_id": "r1a2...", "seq": 3, "ts": 1722700000.1, "kind": "span",
     "process_index": 0, "process_count": 1, ...event fields...}

Standard event kinds written by the wired entry points (dispatch.solve /
solve_transition / bench.py):

  run_start    — config fingerprint (io_utils.checkpoint.config_fingerprint)
                 + free-form metadata + the runtime identity (jax/jaxlib
                 versions, platform fingerprint), first event of every run
  span         — a named wall-clock span (diagnostics/trace.py), nested
                 spans carried as children
  telemetry    — a SolveTelemetry summary (diagnostics/telemetry.py) for one
                 solver context
  verdict      — a convergence verdict (context, converged, iterations,
                 distance, tol)
  degradation  — a counted degradation event (accel safeguard trip storm,
                 push-forward fallback, ...) — ops/pushforward.py emits
                 these through the active-ledger hook below
  metric       — a benchmark record (bench.py writes every metric line it
                 prints)
  heartbeat    — a live progress record (diagnostics/progress.py heartbeat
                 stride; rendered by `python -m aiyagari_tpu watch`)
  host_skew    — a mesh rendezvous probe (diagnostics/skew.py)
  serve_request / cache_hit / coalesce / warmup
               — the persistent solve service's per-request trail, cache
                 lookups, batch formations, and warm-pool compiles
                 (serve/; rendered by report and summarized by watch)

Pod sharding (the multi-host story, docs/USAGE.md "Pod observatory"):
every event is stamped with this host's `process_index`/`process_count`,
and under a multi-process JAX runtime each host writes its OWN shard —
`ledger.jsonl` becomes `ledger.p{k}.jsonl` — so hosts never interleave
writes into one file across DCN filesystems. `merge_ledgers(paths)` joins
the shards back into one run-id-grouped, time-ordered stream (torn tail
lines on live files tolerated), and `read_ledger(..., follow=True)` tails
ONE growing shard as a generator (the single-file tail primitive; the
watch CLI instead re-merges the whole shard set every frame so
late-joining hosts' shards appear).

Reading back: `read_ledger(path)` returns the parsed events;
`python -m aiyagari_tpu report <ledger.jsonl>` renders them
(diagnostics/health.py). Records are coerced through
diagnostics.logging.coerce_record, so numpy/jnp scalars serialize.

The ACTIVE-LEDGER hook: deep device code (the push-forward fallback
counter) cannot thread a ledger handle through jit static args; it calls
`ledger.emit(kind, **fields)`, which appends to whatever ledger is active
on this thread (`with ledger.activate(led): ...`) and is a no-op otherwise.
"""

from __future__ import annotations

import contextlib
import glob as _glob
import json
import os
import threading
import time
import uuid
from pathlib import Path
from typing import Iterator, Optional

from aiyagari_tpu.diagnostics.logging import _json_default, coerce_record

__all__ = [
    "RunLedger",
    "activate",
    "active_ledger",
    "emit",
    "merge_ledgers",
    "read_ledger",
    "shard_path",
    "shard_paths",
]


def new_run_id() -> str:
    return uuid.uuid4().hex[:16]


def _process_topology() -> tuple:
    """This host's (process_index, process_count) — backend-init-free (the
    distributed global state, parallel/distributed.peek_process_topology).
    (0, 1) whenever jax (or the distributed runtime) is not up."""
    try:
        from aiyagari_tpu.parallel.distributed import peek_process_topology

        return peek_process_topology()
    except Exception:
        return 0, 1


def _runtime_identity() -> dict:
    """jax/jaxlib versions + platform fingerprint for run_start — the
    identity a merged pod ledger needs per shard (a version-skewed host is
    the FIRST thing a straggler investigation rules out). Backend-init-free
    and best-effort: fields are omitted rather than guessed."""
    out: dict = {}
    try:
        import jax

        out["jax_version"] = jax.__version__
    except Exception:
        return out
    try:
        import jaxlib

        out["jaxlib_version"] = jaxlib.__version__
    except Exception:
        pass
    try:
        # The compile/tuning caches' host identity (backend + CPU
        # stepping). platform_fingerprint resolves jax.default_backend(),
        # which INITIALIZES a backend on first call — on a pod that would
        # wreck a jax.distributed.initialize still to come (and stamp a
        # (0, 1) topology), so the field is recorded only when a backend
        # already exists; omitted otherwise.
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            from aiyagari_tpu.tuning.autotuner import platform_fingerprint

            out["platform_fingerprint"] = platform_fingerprint()
    except Exception:
        pass
    return out


def shard_path(path, k: int) -> Path:
    """The per-host shard file of a requested ledger path: `ledger.jsonl`
    -> `ledger.p{k}.jsonl` (suffix preserved so shards stay JSONL-typed)."""
    p = Path(path)
    if p.suffix:
        return p.with_name(f"{p.stem}.p{int(k)}{p.suffix}")
    return p.with_name(f"{p.name}.p{int(k)}")


def _shard_glob(path) -> str:
    """The glob matching a path's host shards. Built by the same name
    surgery as shard_path — never by substring replacement over the whole
    path, which would corrupt directories or stems that themselves
    contain \".p0\". `[0-9]*` over-matches (e.g. `.p1x`); callers filter
    by the integer-index parse."""
    p = Path(path)
    if p.suffix:
        return str(p.with_name(f"{p.stem}.p[0-9]*{p.suffix}"))
    return str(p.with_name(f"{p.name}.p[0-9]*"))


def shard_paths(path) -> list:
    """Every on-disk file belonging to a requested ledger path: the base
    file (single-process runs) plus any host shards, shard-index ordered."""
    p = Path(path)
    out = [p] if p.exists() else []
    shards = []
    for s in _glob.glob(_shard_glob(p)):
        stem = Path(s).stem
        try:
            idx = int(stem.rsplit(".p", 1)[1])
        except (IndexError, ValueError):
            continue
        shards.append((idx, Path(s)))
    out.extend(sp for _, sp in sorted(shards))
    return out


def _shared_run_id() -> str:
    """One run id for every host of a multi-process job: process 0 draws it
    and broadcasts (the SPMD channel that already synchronizes every mesh
    program). Falls back to a local id if the collective is unavailable —
    merge_ledgers then still merges, it just cannot join the shards into
    one run."""
    try:
        import numpy as np
        from jax.experimental import multihost_utils

        local = np.frombuffer(uuid.uuid4().bytes, np.uint8).copy()
        shared = np.asarray(multihost_utils.broadcast_one_to_all(local))
        return shared.tobytes().hex()[:16]
    except Exception:
        return new_run_id()


class RunLedger:
    """Append-only JSONL event store for one run.

    Thread-safe; append-only by construction (the file is opened in "a"
    mode per event, so concurrent writers from different threads
    interleave whole lines — POSIX O_APPEND — rather than corrupt).

    Under a multi-process JAX runtime each host writes its own shard
    (`shard_path(path, process_index)`) under a SHARED run id (process 0
    broadcasts it), and every event carries the host stamp. Tests (and
    single-process shard simulations) may pass `process_index` /
    `process_count` explicitly; an explicit `process_index` always selects
    the shard file."""

    def __init__(self, path, *, run_id: Optional[str] = None,
                 config=None, meta: Optional[dict] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        pid, count = _process_topology()
        if process_count is not None:
            count = int(process_count)
        sharded = process_index is not None or count > 1
        if process_index is not None:
            pid = int(process_index)
        self.process_index = pid
        self.process_count = count
        self.base_path = Path(path)
        self.path = shard_path(path, pid) if sharded else Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if run_id is None:
            run_id = _shared_run_id() if count > 1 else new_run_id()
        self.run_id = run_id
        self._seq = 0
        self._lock = threading.Lock()
        start = {"pid": os.getpid(), **_runtime_identity(), **(meta or {})}
        if config is not None:
            from aiyagari_tpu.io_utils.checkpoint import config_fingerprint

            cfgs = config if isinstance(config, (tuple, list)) else (config,)
            start["config_fingerprint"] = config_fingerprint(*cfgs)
            start["config"] = [repr(c) for c in cfgs]
        self.event("run_start", **start)

    def event(self, kind: str, **fields) -> dict:
        """Append one event; returns the written record (coerced)."""
        with self._lock:
            rec = {"run_id": self.run_id, "seq": self._seq,
                   "ts": round(time.time(), 4), "kind": kind,
                   "process_index": self.process_index,
                   "process_count": self.process_count,
                   **coerce_record(fields)}
            self._seq += 1
            with self.path.open("a") as f:
                f.write(json.dumps(rec, default=_json_default) + "\n")
        return rec

    # -- convenience writers for the standard kinds ------------------------

    def telemetry(self, context: str, tele) -> None:
        """Store a SolveTelemetry summary (or a pre-built summary dict)."""
        from aiyagari_tpu.diagnostics.telemetry import (
            SolveTelemetry,
            telemetry_summary,
        )

        if isinstance(tele, SolveTelemetry):
            tele = telemetry_summary(tele)
        if tele is not None:
            self.event("telemetry", context=context, summary=tele)

    def verdict(self, context: str, *, converged, iterations, distance,
                tol, **extra) -> None:
        self.event("verdict", context=context, converged=bool(converged),
                   iterations=int(iterations), distance=float(distance),
                   tol=float(tol), **extra)

    def span(self, record: dict) -> None:
        self.event("span", **record)

    def metric(self, record: dict) -> None:
        self.event("metric", **record)


def _parse_lines(path, *, tolerate_torn: bool) -> list:
    """Parse one shard's lines. A torn FINAL line is a live writer's
    in-flight event: tolerated (skipped) when asked; a torn line anywhere
    else is corruption and always raises."""
    events = []
    with open(path) as f:
        lines = f.readlines()
    for i, ln in enumerate(lines):
        ln = ln.strip()
        if not ln:
            continue
        try:
            events.append(json.loads(ln))
        except json.JSONDecodeError:
            if tolerate_torn and i == len(lines) - 1:
                break
            raise
    return events


def read_ledger(path, *, follow: bool = False, poll_seconds: float = 0.25,
                stop=None, tolerate_torn: bool = False):
    """Parse a ledger JSONL back into its event dicts (the round-trip the
    bench CI test pins). Blank lines are skipped; a torn final line (a
    crashed writer) raises — a ledger that cannot round-trip must be loud —
    unless `tolerate_torn` opts into skipping it (live files).

    follow=True returns a GENERATOR that tails the file instead: complete
    lines are yielded as events as they are appended (a torn tail stays
    buffered until its writer finishes the line), polling every
    `poll_seconds`; `stop` (a nullary callable) ends the tail. This is
    the single-shard tail primitive (external consumers streaming one
    host's events); the watch CLI re-merges whole shard sets per frame
    instead so late-joining hosts appear."""
    if follow:
        return _follow_ledger(path, poll_seconds=poll_seconds, stop=stop)
    return _parse_lines(path, tolerate_torn=tolerate_torn)


def _follow_ledger(path, *, poll_seconds: float, stop) -> Iterator[dict]:
    buf = ""
    pos = 0
    while True:
        try:
            with open(path) as f:
                f.seek(pos)
                buf += f.read()
                pos = f.tell()
        except FileNotFoundError:
            pass
        while "\n" in buf:
            ln, buf = buf.split("\n", 1)
            if ln.strip():
                yield json.loads(ln)
        if stop is not None and stop():
            return
        time.sleep(poll_seconds)


def _merge_files(paths) -> list:
    """Expand the requested paths into concrete shard files: existing
    files pass through, glob patterns expand, and a base path whose host
    shards exist on disk expands to them (the pod case: the operator names
    `ledger.jsonl`, the hosts wrote `ledger.p{k}.jsonl`). De-duplicated,
    deterministic order."""
    files: list = []
    for p in paths:
        p = str(p)
        if os.path.exists(p):
            expanded = shard_paths(p) or [Path(p)]
        elif _glob.glob(p):
            expanded = [Path(g) for g in sorted(_glob.glob(p))]
        else:
            expanded = shard_paths(p)
            if not expanded:
                raise FileNotFoundError(
                    f"no ledger file, shard, or glob match for {p!r}")
        files.extend(expanded)
    seen = set()
    out = []
    for f in files:
        key = os.path.abspath(str(f))
        if key not in seen:
            seen.add(key)
            out.append(Path(key))
    return out


def merge_ledgers(paths, *, tolerate_torn: bool = True) -> list:
    """Join host shards into ONE event stream: events are grouped by run
    id (a pod run's shards share the broadcast run id, so its hosts join
    into a single run), each run's events ordered monotonically by
    timestamp (ties broken by host then per-host sequence — each shard's
    own order is always preserved), and runs ordered by first appearance.
    `paths` may mix concrete files, glob patterns, and base paths with
    on-disk shards. Torn tail lines (live writers) are tolerated by
    default; pass tolerate_torn=False for the strict post-hoc read."""
    events: list = []
    for f in _merge_files(paths if isinstance(paths, (list, tuple))
                          else [paths]):
        events.extend(_parse_lines(f, tolerate_torn=tolerate_torn))
    groups: dict = {}
    for ev in events:
        groups.setdefault(ev.get("run_id", "?"), []).append(ev)
    key = lambda e: (e.get("ts", 0.0), e.get("process_index", 0),  # noqa: E731
                     e.get("seq", 0))
    merged: list = []
    for run_id, evs in sorted(groups.items(),
                              key=lambda kv: min(key(e) for e in kv[1])):
        merged.extend(sorted(evs, key=key))
    return merged


# -- active-ledger hook (thread-local + process fallback) ------------------

_tls = threading.local()
# Process-wide fallback: jax.debug.callback events (the push-forward
# degradation counter) fire on the runtime's callback thread, where the
# activating thread's local is invisible — without this fallback those
# events would silently vanish. A STACK, not a single slot: overlapping
# activations from different threads exit in arbitrary order, and a
# save/restore slot would let the first exit re-point (or null out) the
# fallback while another thread's run is still live. Each exit removes its
# own entry; the fallback is the most recent still-active ledger. The
# thread-local still takes precedence on the activating thread itself.
_proc_lock = threading.Lock()
_proc_stack: list = []


def active_ledger() -> Optional[RunLedger]:
    led = getattr(_tls, "ledger", None)
    if led is not None:
        return led
    with _proc_lock:
        return _proc_stack[-1] if _proc_stack else None


@contextlib.contextmanager
def activate(led: Optional[RunLedger]) -> Iterator[Optional[RunLedger]]:
    """Scope `led` as the active ledger; `emit` routes to it. Scoped
    thread-locally AND as the process fallback (async debug-callback
    threads read the fallback). None is allowed (and makes the block a
    no-op scope), so call sites can pass their optional ledger straight
    through."""
    prev = getattr(_tls, "ledger", None)
    _tls.ledger = led
    if led is not None:
        # Each activation scope is one observed run: reset the
        # route-decision dedup set (tuning/autotuner._record_decision)
        # so a solve re-run on the same ledger records its own
        # route_decision events — exactly one per knob per activation.
        led.__dict__.pop("_route_decisions_emitted", None)
        with _proc_lock:
            _proc_stack.append(led)
    try:
        yield led
    finally:
        _tls.ledger = prev
        if led is not None:
            with _proc_lock:
                for i in range(len(_proc_stack) - 1, -1, -1):
                    if _proc_stack[i] is led:
                        del _proc_stack[i]
                        break


def emit(kind: str, **fields) -> None:
    """Append to the active ledger, if any — the hook deep code (async
    debug callbacks, solver internals) uses without holding a handle."""
    led = active_ledger()
    if led is not None:
        led.event(kind, **fields)
