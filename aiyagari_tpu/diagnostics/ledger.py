"""Run ledger: an append-only JSONL event store keyed by a run id — every
solve's traceable, exportable flight record.

The ROADMAP's calibration item will fit millions of economies whose
failures must be diagnosable WITHOUT re-running; the serve item needs a
durable record of what each request did. The ledger is the storage half of
that story: one JSONL file per run (or shared across runs — events carry
their run id), each line one event:

    {"run_id": "r1a2...", "seq": 3, "ts": 1722700000.1, "kind": "span",
     ...event fields...}

Standard event kinds written by the wired entry points (dispatch.solve /
solve_transition / bench.py):

  run_start    — config fingerprint (io_utils.checkpoint.config_fingerprint)
                 + free-form metadata, first event of every run
  span         — a named wall-clock span (diagnostics/trace.py), nested
                 spans carried as children
  telemetry    — a SolveTelemetry summary (diagnostics/telemetry.py) for one
                 solver context
  verdict      — a convergence verdict (context, converged, iterations,
                 distance, tol)
  degradation  — a counted degradation event (accel safeguard trip storm,
                 push-forward fallback, ...) — ops/pushforward.py emits
                 these through the active-ledger hook below
  metric       — a benchmark record (bench.py writes every metric line it
                 prints)

Reading back: `read_ledger(path)` returns the parsed events;
`python -m aiyagari_tpu report <ledger.jsonl>` renders them
(diagnostics/health.py). Records are coerced through
diagnostics.logging.coerce_record, so numpy/jnp scalars serialize.

The ACTIVE-LEDGER hook: deep device code (the push-forward fallback
counter) cannot thread a ledger handle through jit static args; it calls
`ledger.emit(kind, **fields)`, which appends to whatever ledger is active
on this thread (`with ledger.activate(led): ...`) and is a no-op otherwise.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from pathlib import Path
from typing import Iterator, Optional

from aiyagari_tpu.diagnostics.logging import _json_default, coerce_record

__all__ = [
    "RunLedger",
    "activate",
    "active_ledger",
    "emit",
    "read_ledger",
]


def new_run_id() -> str:
    return uuid.uuid4().hex[:16]


class RunLedger:
    """Append-only JSONL event store for one run.

    Thread-safe; append-only by construction (the file is opened in "a"
    mode per event, so concurrent writers from different processes
    interleave whole lines — POSIX O_APPEND — rather than corrupt)."""

    def __init__(self, path, *, run_id: Optional[str] = None,
                 config=None, meta: Optional[dict] = None):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.run_id = run_id or new_run_id()
        self._seq = 0
        self._lock = threading.Lock()
        start = {"pid": os.getpid(), **(meta or {})}
        if config is not None:
            from aiyagari_tpu.io_utils.checkpoint import config_fingerprint

            cfgs = config if isinstance(config, (tuple, list)) else (config,)
            start["config_fingerprint"] = config_fingerprint(*cfgs)
            start["config"] = [repr(c) for c in cfgs]
        self.event("run_start", **start)

    def event(self, kind: str, **fields) -> dict:
        """Append one event; returns the written record (coerced)."""
        with self._lock:
            rec = {"run_id": self.run_id, "seq": self._seq,
                   "ts": round(time.time(), 4), "kind": kind,
                   **coerce_record(fields)}
            self._seq += 1
            with self.path.open("a") as f:
                f.write(json.dumps(rec, default=_json_default) + "\n")
        return rec

    # -- convenience writers for the standard kinds ------------------------

    def telemetry(self, context: str, tele) -> None:
        """Store a SolveTelemetry summary (or a pre-built summary dict)."""
        from aiyagari_tpu.diagnostics.telemetry import (
            SolveTelemetry,
            telemetry_summary,
        )

        if isinstance(tele, SolveTelemetry):
            tele = telemetry_summary(tele)
        if tele is not None:
            self.event("telemetry", context=context, summary=tele)

    def verdict(self, context: str, *, converged, iterations, distance,
                tol, **extra) -> None:
        self.event("verdict", context=context, converged=bool(converged),
                   iterations=int(iterations), distance=float(distance),
                   tol=float(tol), **extra)

    def span(self, record: dict) -> None:
        self.event("span", **record)

    def metric(self, record: dict) -> None:
        self.event("metric", **record)


def read_ledger(path) -> list:
    """Parse a ledger JSONL back into its event dicts (the round-trip the
    bench CI test pins). Blank lines are skipped; a torn final line (a
    crashed writer) raises — a ledger that cannot round-trip must be loud."""
    events = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                events.append(json.loads(ln))
    return events


# -- active-ledger hook (thread-local + process fallback) ------------------

_tls = threading.local()
# Process-wide fallback: jax.debug.callback events (the push-forward
# degradation counter) fire on the runtime's callback thread, where the
# activating thread's local is invisible — without this fallback those
# events would silently vanish. A STACK, not a single slot: overlapping
# activations from different threads exit in arbitrary order, and a
# save/restore slot would let the first exit re-point (or null out) the
# fallback while another thread's run is still live. Each exit removes its
# own entry; the fallback is the most recent still-active ledger. The
# thread-local still takes precedence on the activating thread itself.
_proc_lock = threading.Lock()
_proc_stack: list = []


def active_ledger() -> Optional[RunLedger]:
    led = getattr(_tls, "ledger", None)
    if led is not None:
        return led
    with _proc_lock:
        return _proc_stack[-1] if _proc_stack else None


@contextlib.contextmanager
def activate(led: Optional[RunLedger]) -> Iterator[Optional[RunLedger]]:
    """Scope `led` as the active ledger; `emit` routes to it. Scoped
    thread-locally AND as the process fallback (async debug-callback
    threads read the fallback). None is allowed (and makes the block a
    no-op scope), so call sites can pass their optional ledger straight
    through."""
    prev = getattr(_tls, "ledger", None)
    _tls.ledger = led
    if led is not None:
        # Each activation scope is one observed run: reset the
        # route-decision dedup set (tuning/autotuner._record_decision)
        # so a solve re-run on the same ledger records its own
        # route_decision events — exactly one per knob per activation.
        led.__dict__.pop("_route_decisions_emitted", None)
        with _proc_lock:
            _proc_stack.append(led)
    try:
        yield led
    finally:
        _tls.ledger = prev
        if led is not None:
            with _proc_lock:
                for i in range(len(_proc_stack) - 1, -1, -1):
                    if _proc_stack[i] is led:
                        del _proc_stack[i]
                        break


def emit(kind: str, **fields) -> None:
    """Append to the active ledger, if any — the hook deep code (async
    debug callbacks, solver internals) uses without holding a handle."""
    led = active_ledger()
    if led is not None:
        led.event(kind, **fields)
