"""Live sweep watch: tail + merge ledger shards into a per-scenario /
per-host progress table (`python -m aiyagari_tpu watch`, ISSUE 14).

The ledger is a post-hoc flight record; a pod validation run needs to SEE
the sweep while it runs — which lane is still iterating, in which stage
dtype, which host is the straggler, what got quarantined — without
printf archaeology. This module is pure host-side consumption: it re-reads
and live-merges the shard files every frame (diagnostics/ledger.
merge_ledgers tolerates the torn tail a live writer leaves), builds a
state table from the observatory's event kinds, and renders it:

  heartbeat   -> per-scenario rows (sweep/round count, residual, stage
                 dtype — progress.sweep_heartbeat's per-lane arrays — or a
                 per-context scalar row for single solves)
  quarantine  -> the lane's verdict column
  verdict     -> the run's closing status line
  host_skew   -> per-axis rendezvous + straggler lines
  mesh_topology / run_start -> the header
  serve_request / cache_hit / coalesce -> the serving summary line
                 (requests, per-status and per-cache-outcome counts, max
                 batch, last queue wait — a serving run is readable with
                 the same CLI, ISSUE 15 satellite)
  fleet_* / tier_promote / l2_tier degradation -> the fleet block (ISSUE
                 20): per-worker table (port, grid class, ready state,
                 request count + rps from that worker's shard, L2
                 promotions) plus route/ack/drain and tier tallies

A single-process ledger (no shards, no mesh, no fleet) degrades to the
same table with one host column — the CLI works identically on a laptop
run, and a single-service serve ledger renders without a fleet block.
"""

from __future__ import annotations

import json
import time

__all__ = ["build_state", "render_state", "watch_main"]


def build_state(events) -> dict:
    """Fold a merged event stream into the render state: one entry per
    run id, in stream order."""
    runs: dict = {}
    for ev in events:
        run = runs.setdefault(ev.get("run_id", "?"), {
            "meta": {}, "mesh": None, "skew": [], "rows": {},
            "verdicts": [], "events": 0, "hosts": set(),
            "regressions": 0, "last_ts": None, "serve": None,
            "fleet": None,
        })
        run["events"] += 1
        run["last_ts"] = ev.get("ts", run["last_ts"])
        if "process_index" in ev:
            run["hosts"].add(int(ev["process_index"]))
        kind = ev.get("kind")
        if kind == "run_start":
            run["meta"] = {k: ev.get(k) for k in
                           ("entry", "config_fingerprint", "jax_version",
                            "platform_fingerprint", "process_count")
                           if ev.get(k) is not None}
        elif kind == "mesh_topology":
            run["mesh"] = {"axes": ev.get("axes") or {},
                           "devices": ev.get("devices"),
                           "processes": ev.get("processes")}
        elif kind == "host_skew":
            run["skew"].append(ev)
        elif kind == "heartbeat":
            _fold_heartbeat(run, ev)
        elif kind == "quarantine":
            sc = ev.get("scenario")
            if sc is not None:
                _quarantine(run, int(sc), ev.get("process_index", 0),
                            ev.get("verdict") or "quarantined",
                            context=ev.get("context"))
        elif kind == "verdict":
            run["verdicts"].append(ev)
        elif kind == "bench_regression":
            run["regressions"] += 1
        elif kind in ("serve_request", "cache_hit", "coalesce"):
            _fold_serve(run, kind, ev)
        elif (kind in ("fleet_worker", "fleet_route", "fleet_ack",
                       "fleet_drain", "fleet_stop", "tier_promote")
              or (kind == "degradation"
                  and ev.get("stage") == "l2_tier")):
            _fold_fleet(run, kind, ev)
    return runs


def _fold_fleet(run: dict, kind: str, ev: dict) -> None:
    """Fold the solve-fabric events (ISSUE 20) into one block: per-worker
    rows keyed by worker index, route/ack/drain tallies, and the L2
    tier's promotion/degradation counts. A tier_promote in a single-
    service run creates the block with tier stats only — the renderer
    skips the worker table when there are no workers."""
    fl = run["fleet"]
    if fl is None:
        fl = run["fleet"] = {
            "workers": {}, "routes": 0, "acks": 0, "drains": 0,
            "replays": 0, "promotions": {}, "l2_degradations": 0,
        }
    if kind == "fleet_worker":
        idx = ev.get("worker")
        w = fl["workers"].setdefault(
            idx if idx is not None else "?",
            {"port": None, "grid": None, "state": "?",
             "warm_seconds": None, "warm_restored": None})
        for field in ("port", "grid", "state", "warm_seconds",
                      "warm_restored"):
            if ev.get(field) is not None:
                w[field] = ev[field]
    elif kind == "fleet_route":
        fl["routes"] += 1
    elif kind == "fleet_ack":
        fl["acks"] += 1
    elif kind == "fleet_drain":
        fl["drains"] += 1
        fl["replays"] += int(ev.get("replayed") or 0)
    elif kind == "tier_promote":
        host = int(ev.get("process_index", 0))
        fl["promotions"][host] = fl["promotions"].get(host, 0) + 1
    elif kind == "degradation":
        fl["l2_degradations"] += 1


def _fold_serve(run: dict, kind: str, ev: dict) -> None:
    """Fold the serving events (ISSUE 15) into one summary block:
    request/status/cache tallies, coalescing batch sizes, queue waits."""
    sv = run["serve"]
    if sv is None:
        sv = run["serve"] = {
            "requests": 0, "statuses": {}, "cache": {},
            "lookups": {}, "coalesced_batches": 0, "max_batch": 0,
            "last_queue_wait_s": None, "by_host": {},
        }
    if kind == "serve_request":
        sv["requests"] += 1
        st = ev.get("status") or "?"
        sv["statuses"][st] = sv["statuses"].get(st, 0) + 1
        ca = ev.get("cache") or "?"
        sv["cache"][ca] = sv["cache"].get(ca, 0) + 1
        sv["max_batch"] = max(sv["max_batch"], int(ev.get("batch") or 1))
        if ev.get("queue_wait_s") is not None:
            sv["last_queue_wait_s"] = ev["queue_wait_s"]
        # Per-shard tallies: in a fleet ledger process_index IS the worker
        # index, so these become the worker table's requests/rps columns.
        host = sv["by_host"].setdefault(
            int(ev.get("process_index", 0)),
            {"requests": 0, "first_ts": None, "last_ts": None})
        host["requests"] += 1
        ts = ev.get("ts")
        if ts is not None:
            if host["first_ts"] is None:
                host["first_ts"] = ts
            host["last_ts"] = ts
    elif kind == "cache_hit":
        oc = ev.get("outcome") or "?"
        sv["lookups"][oc] = sv["lookups"].get(oc, 0) + 1
    elif kind == "coalesce":
        sv["coalesced_batches"] += 1
        sv["max_batch"] = max(sv["max_batch"], int(ev.get("batch") or 1))


def _row(run: dict, scenario, host, *, context=None) -> dict:
    # Rows are keyed per context too: one run can carry several sweep
    # contexts (a transition sweep's stationary-anchor GE rounds heartbeat
    # as "aiyagari_sweep", its own rounds as "mit_transition_sweep"), and
    # a shared (scenario, host) key would let them overwrite each other.
    return run["rows"].setdefault(
        (scenario if scenario is not None else "-", int(host),
         context or "-"),
        {"context": context, "sweeps": None, "residual": None,
         "dtype": None, "verdict": "running", "quarantined": False})


def _quarantine(run: dict, scenario, host, verdict, *, context=None) -> None:
    """Mark a lane's verdict. A quarantine event without a context applies
    to every context's row for that (scenario, host) lane — the lane is
    quarantined, whichever loop is reporting it."""
    matched = [row for (sc, h, c), row in run["rows"].items()
               if sc == scenario and h == int(host)
               and (context is None or c == (context or "-"))]
    if not matched:
        matched = [_row(run, scenario, host, context=context)]
    for row in matched:
        row["verdict"] = verdict


def _fold_heartbeat(run: dict, ev: dict) -> None:
    host = ev.get("process_index", 0)
    gap = ev.get("gap", ev.get("distance"))
    sweeps = ev.get("round", ev.get("iteration"))
    if isinstance(gap, list):
        # A lockstep sweep round (or a vmapped solve's batched progress
        # record): one row per scenario lane. A list-shaped iteration
        # count is per-lane too — index it alongside the residual.
        conv = ev.get("converged") or [None] * len(gap)
        quar = ev.get("quarantined") or [False] * len(gap)
        for i, g in enumerate(gap):
            row = _row(run, i, host, context=ev.get("context"))
            row.update(context=ev.get("context"),
                       sweeps=(sweeps[i] if isinstance(sweeps, list)
                               and i < len(sweeps) else sweeps),
                       residual=g, dtype=ev.get("dtype"))
            if i < len(quar) and quar[i]:
                row["quarantined"] = True
                row["verdict"] = "quarantined"
            elif i < len(conv) and conv[i]:
                row["verdict"] = "converged"
            elif row["verdict"] != "quarantined":
                row["verdict"] = "running"
    else:
        row = _row(run, None, host, context=ev.get("context"))
        row.update(context=ev.get("context"), sweeps=sweeps, residual=gap,
                   dtype=ev.get("dtype"))


def _fmt(v, width, float_fmt="{:.3e}") -> str:
    if v is None:
        s = "-"
    elif isinstance(v, float):
        s = float_fmt.format(v)
    else:
        s = str(v)
    return s.ljust(width)


def render_state(runs: dict) -> str:
    """One text frame for every run in the state."""
    lines = []
    for run_id, run in runs.items():
        hosts = sorted(run["hosts"]) or [0]
        head = [f"run {run_id}", f"events={run['events']}",
                f"hosts={len(hosts)}"]
        if run["meta"].get("entry"):
            head.insert(1, f"entry={run['meta']['entry']}")
        mesh = run.get("mesh")
        if mesh and mesh["axes"]:
            head.append("mesh=" + " x ".join(
                f"{a}={s}" for a, s in mesh["axes"].items()))
        lines.append("  ".join(head))
        for ev in run["skew"]:
            bit = (f"  skew {ev.get('axis')}: rendezvous "
                   f"{ev.get('rendezvous_seconds')}s  "
                   f"lag spread {ev.get('lag_spread_seconds')}s  "
                   f"{ev.get('verdict')}")
            if ev.get("straggler") is not None:
                bit += f" (host {ev['straggler']})"
            lines.append(bit)
        if run["rows"]:
            lines.append("  scenario  host  sweeps  residual   dtype     "
                         "verdict      quarantine  context")
            # Numeric scenario ids sort numerically (10 after 9, not
            # after 1); the "-" single-solve placeholder sorts last.
            for (sc, host, _ctx), row in sorted(
                    run["rows"].items(),
                    key=lambda kv: ((1, str(kv[0][0]))
                                    if isinstance(kv[0][0], str)
                                    else (0, kv[0][0]),
                                    kv[0][1], str(kv[0][2]))):
                lines.append(
                    "  " + _fmt(sc, 10) + _fmt(host, 6)
                    + _fmt(row["sweeps"], 8) + _fmt(row["residual"], 11)
                    + _fmt(row["dtype"], 10) + _fmt(row["verdict"], 13)
                    + _fmt("yes" if row["quarantined"] else "-", 12)
                    + _fmt(row["context"], 1).rstrip())
        sv = run.get("serve")
        if sv:
            bits = [f"serve: {sv['requests']} request(s)"]
            if sv["cache"]:
                bits.append("cache " + "/".join(
                    f"{k}={v}" for k, v in sorted(sv["cache"].items())))
            if sv["statuses"]:
                bits.append("status " + "/".join(
                    f"{k}={v}" for k, v in sorted(sv["statuses"].items())))
            bits.append(f"batches={sv['coalesced_batches']}")
            bits.append(f"max batch={sv['max_batch']}")
            if sv["last_queue_wait_s"] is not None:
                bits.append(f"last wait={sv['last_queue_wait_s']}s")
            lines.append("  " + "  ".join(bits))
        fl = run.get("fleet")
        if fl:
            promos = sum(fl["promotions"].values())
            lines.append(
                f"  fleet: {len(fl['workers'])} worker(s)  "
                f"routes={fl['routes']} acks={fl['acks']} "
                f"unacked={max(0, fl['routes'] - fl['acks'])} "
                f"drains={fl['drains']} replays={fl['replays']}  "
                f"tier promotions={promos} "
                f"degradations={fl['l2_degradations']}")
            if fl["workers"]:
                by_host = (run.get("serve") or {}).get("by_host", {})
                lines.append("  worker  port   grid  state      requests"
                             "  rps      l2_promotions  warm_s")
                for idx in sorted(fl["workers"],
                                  key=lambda k: (isinstance(k, str), k)):
                    w = fl["workers"][idx]
                    h = by_host.get(idx if isinstance(idx, int) else -1,
                                    {})
                    n = h.get("requests", 0)
                    span = ((h.get("last_ts") or 0)
                            - (h.get("first_ts") or 0))
                    rps = round(n / span, 2) if n and span > 0 else None
                    lines.append(
                        "  " + _fmt(idx, 8) + _fmt(w["port"], 7)
                        + _fmt(w["grid"], 6) + _fmt(w["state"], 11)
                        + _fmt(n, 10) + _fmt(rps, 9, "{:.2f}")
                        + _fmt(fl["promotions"].get(
                            idx if isinstance(idx, int) else -1, 0), 15)
                        + _fmt(w["warm_seconds"], 1, "{:.2f}").rstrip())
        for ev in run["verdicts"]:
            status = "converged" if ev.get("converged") else "NOT CONVERGED"
            lines.append(f"  done {ev.get('context')}: {status} after "
                         f"{ev.get('iterations')} iterations")
        if run["regressions"]:
            lines.append(f"  bench regressions: {run['regressions']}")
    return "\n".join(lines) if lines else "(no events yet)"


def watch_main(argv) -> int:
    """`python -m aiyagari_tpu watch <ledger|shard|glob>...`: tail and
    live-merge the shards, re-rendering the table every --interval
    seconds. --once renders a single frame (scripts, tests); --json dumps
    the folded state instead of the table."""
    import argparse

    from aiyagari_tpu.diagnostics.ledger import merge_ledgers

    ap = argparse.ArgumentParser(prog="aiyagari_tpu watch")
    ap.add_argument("paths", nargs="+",
                    help="ledger files, host shards, or glob patterns; a "
                         "base path with on-disk .p{k} shards expands to "
                         "them (re-expanded every frame, so shards from "
                         "late-joining hosts appear live)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between frames (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the folded state as JSON instead of a table")
    args = ap.parse_args(argv)

    def frame() -> str:
        try:
            events = merge_ledgers(args.paths, tolerate_torn=True)
        except FileNotFoundError:
            return f"(waiting for {', '.join(args.paths)})"
        runs = build_state(events)
        if args.json:
            for run in runs.values():
                run["hosts"] = sorted(run["hosts"])
                run["rows"] = {f"{sc}/{host}/{ctx}": row
                               for (sc, host, ctx), row
                               in run["rows"].items()}
            return json.dumps(runs, indent=2, default=str)
        return render_state(runs)

    if args.once:
        print(frame())
        return 0
    try:
        while True:
            print(frame(), flush=True)
            print("---", flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0
