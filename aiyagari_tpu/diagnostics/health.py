"""Health certificates: one report that says whether a solve can be trusted.

Den Haan (2010)'s program — and the accuracy literature it anchors
(PAPERS.md) — is that the error TRAJECTORY and the off-grid Euler residuals
certify a solution; terminal convergence flags do not. This module
assembles that certificate from what a solve already carries:

  * Euler-equation error percentiles (utils/accuracy.euler_equation_errors,
    consumption-equivalent log10 units) at the converged policies;
  * the distribution's mass defect |sum(mu) - 1|;
  * policy monotonicity and push-forward fallback tallies (a non-monotone
    savings policy silently degrades every scatter-free route);
  * the residual trajectory's SHAPE (diagnostics/telemetry.py recorders):
    geometric decay vs stall vs oscillation — a loop that exits at
    max_iter while limit-cycling reports the same scalars as one that
    genuinely converged, and only the trajectory tells them apart.

`health_report(result, model=...)` returns the report as a dict;
`EquilibriumResult.health()` / `TransitionResult.health()` delegate here.
`render_report` pretty-prints it, and the `python -m aiyagari_tpu report
<ledger.jsonl>` CLI (report_main) renders a whole run ledger.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "diagnose_trajectory",
    "health_report",
    "render_report",
    "report_main",
]

# Trajectory-shape thresholds. A tail window whose best residual improves
# by less than _STALL_GAIN over the window is "stalled"; a window where
# more than _OSC_FRAC of consecutive differences flip sign is
# "oscillating". Windows are short (tail behavior is what matters) and the
# verdicts are advisory labels, not hard failures.
_TAIL = 16
_STALL_GAIN = 0.5     # tail must improve by >= 2x over the window
_OSC_FRAC = 0.6


def diagnose_trajectory(residuals) -> dict:
    """Shape diagnosis of one residual trajectory (chronological, host).

    Returns {"sweeps", "first", "final", "decay_rate", "stalled",
    "oscillating", "nonfinite"}: decay_rate is the per-sweep geometric
    factor fitted to the finite positive tail (NaN when it cannot be
    estimated), `stalled` and `oscillating` the tail-window verdicts
    described above, and `nonfinite` True when the RAW trajectory ends on
    a non-finite residual — the NaN-poisoned-solve signature the report
    must never launder into a clean-looking summary (the `first`/`final`
    fields are computed over the finite entries only)."""
    raw = np.asarray(residuals, np.float64).reshape(-1)
    r = raw[np.isfinite(raw)]
    out = {"sweeps": int(len(r)),
           "first": float(r[0]) if len(r) else None,
           "final": float(r[-1]) if len(r) else None,
           "nonfinite": bool(len(raw) and not np.isfinite(raw[-1])),
           "decay_rate": None, "stalled": False, "oscillating": False}
    if len(r) < 4:
        return out
    tail = r[-min(_TAIL, len(r)):]
    pos = tail[tail > 0.0]
    if len(pos) >= 3:
        # Geometric fit: median ratio of consecutive positive residuals —
        # robust to the occasional safeguard spike.
        out["decay_rate"] = float(np.median(pos[1:] / pos[:-1]))
    # Stall: the tail's end is not meaningfully below its start.
    if tail[0] > 0 and tail[-1] > _STALL_GAIN * tail[0]:
        out["stalled"] = True
    # Oscillation: consecutive differences keep flipping sign (limit cycle
    # around the fixed point — the f32 flat-top wobble signature).
    d = np.diff(tail)
    nz = d[d != 0.0]
    if len(nz) >= 4:
        flips = np.mean(np.sign(nz[1:]) != np.sign(nz[:-1]))
        if flips >= _OSC_FRAC:
            out["oscillating"] = True
    return out


def _policy_monotonicity(policy_k) -> dict:
    pk = np.asarray(policy_k)
    viol = np.sum(pk[..., 1:] < pk[..., :-1])
    return {"monotone": bool(viol == 0), "violations": int(viol)}


def _euler_percentiles(result, model) -> dict | None:
    sol = getattr(result, "solution", None)
    if (model is None or sol is None
            or getattr(sol, "policy_c", None) is None):
        return None
    if not hasattr(model, "a_grid"):
        # Accept the AiyagariConfig the caller handed to solve() — the
        # discretized model is cheap to rebuild from it.
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        model = AiyagariModel.from_config(model)
    if getattr(model.config, "endogenous_labor", False):
        # The midpoint Euler residual below assumes the exogenous-labor
        # budget; the labor variant's intratemporal FOC is not wired yet.
        return None
    from aiyagari_tpu.utils.accuracy import euler_equation_errors
    from aiyagari_tpu.utils.firm import wage_from_r

    prefs = model.preferences
    tech = model.config.technology
    r = float(result.r)
    w = float(wage_from_r(r, tech.alpha, tech.delta))
    log10_err, mask = euler_equation_errors(
        sol.policy_c, sol.policy_k, model.a_grid, model.s, model.P, r, w,
        model.amin, sigma=float(prefs.sigma), beta=float(prefs.beta))
    err = np.asarray(log10_err)[np.asarray(mask)]
    if err.size == 0:
        return None
    return {
        "p50_log10": float(np.percentile(err, 50)),
        "p90_log10": float(np.percentile(err, 90)),
        "p99_log10": float(np.percentile(err, 99)),
        "max_log10": float(err.max()),
        "points": int(err.size),
    }


def health_report(result, model=None) -> dict:
    """Assemble the health certificate for an EquilibriumResult or
    TransitionResult (duck-typed: anything carrying the relevant fields).
    `model` (an AiyagariModel) unlocks the Euler-error percentiles."""
    from aiyagari_tpu.diagnostics.telemetry import (
        SolveTelemetry,
        telemetry_summary,
        telemetry_trajectory,
    )

    report: dict = {"kind": type(result).__name__,
                    "converged": bool(getattr(result, "converged", False))}

    # Outer-loop residual trajectory (host recorder on the result).
    tele = getattr(result, "telemetry", None)
    if isinstance(tele, SolveTelemetry):
        report["outer"] = {
            **(telemetry_summary(tele) or {}),
            "trajectory": diagnose_trajectory(telemetry_trajectory(tele)),
        }

    # Inner (household/distribution) recorder, when the solve carried one.
    sol = getattr(result, "solution", None)
    inner = getattr(sol, "telemetry", None) if sol is not None else None
    if isinstance(inner, SolveTelemetry) and np.ndim(inner.count) == 0:
        report["inner"] = {
            **(telemetry_summary(inner) or {}),
            "trajectory": diagnose_trajectory(telemetry_trajectory(inner)),
        }

    mu = getattr(result, "mu", None)
    if mu is not None:
        mass = float(np.sum(np.asarray(mu, np.float64)))
        report["distribution"] = {
            "mass_defect": abs(mass - 1.0),
            "min_mass": float(np.min(np.asarray(mu))),
        }

    if sol is not None and getattr(sol, "policy_k", None) is not None:
        report["policy"] = _policy_monotonicity(sol.policy_k)

    euler = _euler_percentiles(result, model)
    if euler is not None:
        report["euler_errors"] = euler

    # Transition results: the round history IS the outer trajectory.
    hist = getattr(result, "max_excess_history", None)
    if hist:
        report["outer"] = report.get("outer") or {}
        report["outer"]["trajectory"] = diagnose_trajectory(hist)
        report["outer"]["rounds"] = len(hist)
        report["outer"]["final_residual"] = float(hist[-1])

    flags = []
    # A trajectory ending on a non-finite residual is ALWAYS flagged —
    # even on a "converged" result (a NaN distance slips through `< tol`
    # criteria silently; the nan verdict must never be laundered by a
    # convergence flag the same NaN fooled). The errors.enforce_convergence
    # counterpart of this rule warns/raises at solve time.
    for side in ("outer", "inner"):
        tr = report.get(side, {}).get("trajectory") or {}
        if tr.get("nonfinite"):
            flags.append(f"{side}-nan-residual")
    # The sentinel's own verdict, when the solve carried one.
    verdict = getattr(result, "verdict", "")
    if verdict:
        report["verdict"] = verdict
        flags.append(f"verdict-{verdict}")
    if not report["converged"]:
        flags.append("not-converged")
        # Trajectory-shape flags explain WHY the iteration cap was hit
        # (stall vs limit cycle vs slow-but-healthy decay). A CONVERGED
        # solve's tail shape is moot — bisection gap trajectories
        # legitimately oscillate while closing, and flagging them would
        # mark every healthy GE solve sick.
        for side in ("outer", "inner"):
            tr = report.get(side, {}).get("trajectory") or {}
            if tr.get("stalled"):
                flags.append(f"{side}-stalled")
            if tr.get("oscillating"):
                flags.append(f"{side}-oscillating")
    if report.get("distribution", {}).get("mass_defect", 0.0) > 1e-8:
        flags.append("mass-defect")
    if report.get("policy", {}).get("monotone") is False:
        flags.append("non-monotone-policy")
    report["flags"] = flags
    report["healthy"] = not flags
    return report


def render_report(report: dict) -> str:
    """Human-readable rendering of one health_report dict."""
    lines = [f"health: {'OK' if report.get('healthy') else 'FLAGS: ' + ', '.join(report.get('flags', []))}"
             f"  ({report.get('kind', '?')}, converged={report.get('converged')})"]
    for side in ("outer", "inner"):
        sec = report.get(side)
        if not sec:
            continue
        tr = sec.get("trajectory") or {}
        bits = [f"{side}: sweeps={sec.get('sweeps', tr.get('sweeps'))}",
                f"final={tr.get('final', sec.get('final_residual'))}"]
        if tr.get("decay_rate") is not None:
            bits.append(f"decay~{tr['decay_rate']:.3g}/sweep")
        if sec.get("accel_trips"):
            bits.append(f"accel_trips={sec['accel_trips']}")
        if sec.get("pushforward_fallbacks"):
            bits.append(f"fallbacks={sec['pushforward_fallbacks']}")
        lines.append("  " + "  ".join(str(b) for b in bits))
    if "distribution" in report:
        lines.append(f"  mass defect: {report['distribution']['mass_defect']:.3e}")
    if "euler_errors" in report:
        e = report["euler_errors"]
        lines.append(
            f"  euler errors (log10): p50={e['p50_log10']:.2f} "
            f"p90={e['p90_log10']:.2f} p99={e['p99_log10']:.2f} "
            f"max={e['max_log10']:.2f} over {e['points']} midpoints")
    if "policy" in report and not report["policy"]["monotone"]:
        lines.append(f"  policy: {report['policy']['violations']} "
                     "monotonicity violations")
    return "\n".join(lines)


def _has_shards(path) -> bool:
    """Whether a requested ledger path needs the merge path: real host
    shards (integer-indexed, per ledger.shard_paths) exist beside it, or
    the path itself is a glob / not a plain file. A non-shard sibling
    like `ledger.prod.jsonl` must NOT flip the single-file read into the
    tolerant merge — the strict torn-line semantics are the point."""
    from pathlib import Path

    from aiyagari_tpu.diagnostics.ledger import shard_paths

    found = shard_paths(path)
    return found != [Path(path)]


def report_main(argv) -> int:
    """`python -m aiyagari_tpu report <ledger.jsonl> [shard2 ...]`: render
    a run ledger — runs, spans, verdicts, telemetry summaries,
    degradations, pod-observatory events — to stdout. Multiple paths (or a
    base path whose host shards exist on disk) are implicitly merged by
    run id in timestamp order (diagnostics/ledger.merge_ledgers), so the
    existing report workflow reads pod output unchanged."""
    import argparse

    from aiyagari_tpu.diagnostics.ledger import merge_ledgers, read_ledger

    ap = argparse.ArgumentParser(prog="aiyagari_tpu report")
    ap.add_argument("ledger", nargs="+",
                    help="run-ledger JSONL file(s); host shards "
                         "(ledger.p{k}.jsonl) and glob patterns are "
                         "merged by run id")
    ap.add_argument("--json", action="store_true",
                    help="emit the parsed events as one JSON document")
    args = ap.parse_args(argv)
    if len(args.ledger) == 1 and not _has_shards(args.ledger[0]):
        # The historical single-file path keeps its strict torn-line
        # semantics (a post-hoc ledger that cannot round-trip is loud).
        events = read_ledger(args.ledger[0])
    else:
        events = merge_ledgers(args.ledger)
    if args.json:
        import json

        print(json.dumps(events, indent=2))
        return 0

    by_run: dict = {}
    for ev in events:
        by_run.setdefault(ev.get("run_id", "?"), []).append(ev)
    for run_id, evs in by_run.items():
        start = next((e for e in evs if e["kind"] == "run_start"), {})
        hosts = {e.get("process_index", 0) for e in evs}
        host_bit = f"  hosts={len(hosts)}" if len(hosts) > 1 else ""
        print(f"run {run_id}  events={len(evs)}  "
              f"fingerprint={start.get('config_fingerprint', '-')}"
              + host_bit)
        for ev in evs:
            k = ev["kind"]
            if k == "run_start":
                continue
            if k == "span":
                print(f"  span {ev.get('name')}: {ev.get('seconds')}s"
                      + (f" (compile {ev.get('compile_s')}s)"
                         if ev.get("compile_s") is not None else ""))
            elif k == "verdict":
                status = "converged" if ev.get("converged") else "NOT CONVERGED"
                print(f"  verdict {ev.get('context')}: {status} after "
                      f"{ev.get('iterations')} iterations "
                      f"(distance {ev.get('distance'):.3e} vs tol {ev.get('tol'):.1e})")
            elif k == "telemetry":
                s = ev.get("summary", {})
                print(f"  telemetry {ev.get('context')}: sweeps={s.get('sweeps')} "
                      f"final={s.get('final_residual')} "
                      f"trips={s.get('accel_trips')} "
                      f"fallbacks={s.get('pushforward_fallbacks')}")
            elif k == "degradation":
                if ev.get("stage") == "l2_tier":
                    print(f"  degradation [l2_tier]: {ev.get('reason')} "
                          f"({ev.get('path', '-')})")
                else:
                    print(f"  degradation: {ev.get('event')} "
                          f"x{ev.get('n', 1)} ({ev.get('route', '-')})")
            elif k == "metric":
                print(f"  metric {ev.get('metric')}: {ev.get('value')} "
                      f"{ev.get('unit', '')}")
            elif k == "route_decision":
                ev_d = ev.get("evidence") or {}
                walls = ev_d.get("walls_us") or {}
                if walls:
                    detail = "  ".join(
                        f"{r}={w:.1f}us" for r, w in
                        sorted(walls.items(), key=lambda kv: kv[1]))
                elif ev_d.get("predicted_us"):
                    detail = "roofline prior: " + "  ".join(
                        f"{r}={w:.1f}us" for r, w in
                        sorted(ev_d["predicted_us"].items(),
                               key=lambda kv: kv[1]))
                else:
                    detail = "shipped default"
                print(f"  route {ev.get('knob')} -> {ev.get('choice')} "
                      f"[{ev.get('source')}, {ev.get('bucket', 'any')}/"
                      f"{ev.get('dtype', 'any')}] {detail}")
            elif k == "analysis":
                print(f"  analysis: {ev.get('findings')} active finding(s) "
                      f"over {ev.get('programs_audited')} program(s), "
                      f"{ev.get('files_linted')} file(s)"
                      + (f", skipped {', '.join(ev['programs_skipped'])}"
                         if ev.get("programs_skipped") else ""))
            elif k == "attribution":
                comp = ev.get("compiled") or {}
                ratio = ev.get("byte_ratio")
                mod = ev.get("modeled") or {}
                print(f"  attribution {ev.get('program')}: compiled "
                      f"{comp.get('bytes_accessed')} B vs modeled "
                      f"{mod.get('hbm_bytes', '-')} B"
                      + (f" (x{ratio})" if ratio is not None else "")
                      + (" ** FUSION-REGRESSION FLAG **"
                         if ev.get("flagged") else ""))
            elif k == "mesh_topology":
                axes = ev.get("axes") or {}
                shape = " x ".join(f"{a}={s}" for a, s in axes.items())
                print(f"  mesh {ev.get('entry', '-')}: {shape} "
                      f"({ev.get('devices')} device(s), "
                      f"{ev.get('processes')} process(es))")
            elif k == "host_skew":
                rec = ev.get("reconciliation") or {}
                bit = (f" vs priced {rec.get('link')} "
                       f"{rec.get('priced_seconds'):.2e}s"
                       if rec.get("priced_seconds") else "")
                strag = (f" (host {ev['straggler']})"
                         if ev.get("straggler") is not None else "")
                print(f"  skew {ev.get('axis')}: rendezvous "
                      f"{ev.get('rendezvous_seconds')}s  lag spread "
                      f"{ev.get('lag_spread_seconds')}s  "
                      f"{ev.get('verdict')}{strag}{bit}")
            elif k == "heartbeat":
                where = (f"@p{ev['process_index']}"
                         if ev.get("process_count", 1) > 1 else "")
                gap = ev.get("gap", ev.get("distance"))
                n = ev.get("round", ev.get("iteration"))
                print(f"  heartbeat {ev.get('context')}{where}: "
                      f"sweep {n}  residual {gap}  "
                      f"dtype {ev.get('dtype', '-')}")
            elif k == "bench_regression":
                print(f"  bench regression [{ev.get('severity')}] "
                      f"{ev.get('metric')}.{ev.get('field')}: "
                      f"{ev.get('reason')} (frozen in {ev.get('source')})")
            elif k == "serve_request":
                print(f"  serve {ev.get('id')} [{ev.get('request_kind')}]: "
                      f"{ev.get('status')}  cache={ev.get('cache')}  "
                      f"wait {ev.get('queue_wait_s')}s  "
                      f"wall {ev.get('wall_s')}s  "
                      f"batch {ev.get('batch')}")
            elif k == "coalesce":
                print(f"  coalesce [{ev.get('request_kind')}]: "
                      f"batch {ev.get('batch')}  queue wait "
                      f"{ev.get('queue_wait_min_s')}-"
                      f"{ev.get('queue_wait_max_s')}s")
            elif k == "cache_hit":
                print(f"  cache {ev.get('outcome')} "
                      f"[{ev.get('lookup')}] for {ev.get('id')}")
            elif k == "warmup":
                if ev.get("skipped"):
                    print(f"  warmup {ev.get('program')}: skipped "
                          f"({ev.get('skipped')})")
                else:
                    aot_bit = " [AOT restore]" if ev.get("restored") else ""
                    print(f"  warmup {ev.get('program')}: "
                          f"{ev.get('compile_seconds')}s{aot_bit}")
            elif k == "fleet_worker":
                print(f"  fleet worker {ev.get('worker')} "
                      f"(port {ev.get('port', '-')}, "
                      f"grid {ev.get('grid', '-')}): {ev.get('state')}"
                      + (f"  warm {ev['warm_seconds']}s"
                         f" ({ev.get('warm_restored', 0)} AOT-restored)"
                         if ev.get("warm_seconds") is not None else ""))
            elif k == "fleet_route":
                print(f"  fleet route {ev.get('rid')}: -> worker "
                      f"{ev.get('worker')} (port {ev.get('port')}, "
                      f"class {ev.get('grid_class')}) {ev.get('path')}")
            elif k == "fleet_ack":
                print(f"  fleet ack {ev.get('rid')}: {ev.get('code')}")
            elif k == "fleet_drain":
                print(f"  fleet drain worker {ev.get('worker')}: "
                      f"replayed {ev.get('replayed')} un-acked request(s) "
                      f"({ev.get('replay_failures', 0)} failed) onto "
                      f"{ev.get('survivors')} survivor(s)")
            elif k == "fleet_stop":
                print(f"  fleet stop: {ev.get('workers')} worker(s) down")
            elif k == "tier_promote":
                print(f"  tier promote [{ev.get('promotion', '-')}]: "
                      f"L2 -> L1 warm material ({ev.get('path', '-')})")
            elif k == "tuning_probe":
                walls = ev.get("walls_us") or {}
                detail = "  ".join(f"{r}={w:.1f}us" for r, w in
                                   sorted(walls.items(), key=lambda kv: kv[1]))
                print(f"  probe {ev.get('knob')} -> {ev.get('choice')} "
                      f"(na={ev.get('na')}, {ev.get('dtype')}) {detail}")
            else:
                print(f"  {k}: " + ", ".join(
                    f"{a}={b}" for a, b in ev.items()
                    if a not in ("run_id", "seq", "ts", "kind")))
    return 0
