"""Device-resident failure sentinels for the hot fixed-point loops.

The framework's while-loops already EXIT on a non-finite residual (every
cond is written `dist >= tol`, which is False for NaN — the NaN-poisoning
contract), but exiting is not the same as DIAGNOSING: the caller sees a
NaN distance and must guess whether it was an interp-window escape, genuine
divergence, or an injected pathology; and two failure shapes the cond
cannot see at all — a stalled iterate wandering at its noise floor and a
divergent iterate whose residual grows for hundreds of sweeps before
overflowing to inf — burn `max_iter` sweeps on garbage. This module makes
failure a FIRST-CLASS loop outcome:

  * `SentinelState` is a tiny pytree (5 scalars) carried INSIDE the
    while_loop. `sentinel_update` watches each sweep's residual for
    non-finite values (verdict "nan", or "escape" when the solver's
    windowed-inversion escape flag is raised), residuals that exceed
    `explode_factor` x the first recorded residual ("explode"), and
    `stall_window` sweeps without a new best residual ("stall").
  * `sentinel_cond` ANDs `verdict == 0` into the loop condition, so the
    first nonzero verdict EARLY-EXITS the loop — a stalled 10k-sweep
    distribution iteration stops after `stall_window` wasted sweeps, not
    at max_iter.
  * Every helper is a COMPILE-TIME no-op when the state is None: the
    telemetry-off discipline of diagnostics/telemetry.py — a sentinel-off
    solve traces to the identical program with the identical carry
    (jaxpr-pinned by tests/test_resilience.py).

The host-side outer loops (GE bisection/batched rounds, transition Newton
rounds) apply the same thresholds through `host_verdict` on their residual
histories, so one verdict taxonomy serves both worlds; the user-facing
knob is `config.SentinelConfig`, wired as `SolverConfig(sentinel=...)`.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import SentinelConfig

__all__ = [
    "SentinelConfig",
    "SentinelState",
    "VERDICT_NAMES",
    "sentinel_init",
    "sentinel_stage_reset",
    "sentinel_update",
    "sentinel_cond",
    "sentinel_leaves",
    "sentinel_from_leaves",
    "sentinel_summary",
    "verdict_name",
    "host_verdict",
]

# Verdict codes, stable across the framework (ledger events, rescue attempt
# records, and the AIYA107 contract all key on the names).
VERDICT_NAMES = ("ok", "nan", "stall", "explode", "escape")
_OK, _NAN, _STALL, _EXPLODE, _ESCAPE = range(5)

# Residuals are watched in f32 for the same reason telemetry records them
# in f32: the state must cross mixed-precision stage boundaries without
# changing pytree structure, and f32 resolves anything the verdicts can
# distinguish.
_DT = jnp.float32


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SentinelState:
    """One loop's failure-watch state. All fields are arrays, so the state
    vmaps/shards with the solve (a scenario batch carries one verdict per
    scenario, leading [S] axis)."""

    verdict: jax.Array     # int32 verdict code (0 = healthy); sticky
    best: jax.Array        # f32 best (lowest) finite residual seen
    first: jax.Array       # f32 first finite residual (explosion reference)
    since_best: jax.Array  # int32 sweeps since `best` last improved
    count: jax.Array       # int32 residuals watched


def sentinel_init(cfg: Optional[SentinelConfig]) -> Optional[SentinelState]:
    """A fresh sentinel for `cfg`, or None when off — the None flows through
    every helper unchanged, so the off path compiles to the exact
    pre-sentinel program."""
    if cfg is None:
        return None
    if cfg.stall_window < 2:
        raise ValueError(
            f"SentinelConfig.stall_window must be >= 2, got {cfg.stall_window}")
    if cfg.explode_factor <= 1.0:
        raise ValueError(
            "SentinelConfig.explode_factor must exceed 1.0, got "
            f"{cfg.explode_factor}")
    inf = jnp.asarray(jnp.inf, _DT)
    return SentinelState(
        verdict=jnp.int32(_OK),
        best=inf,
        first=inf,
        since_best=jnp.int32(0),
        count=jnp.int32(0),
    )


def sentinel_stage_reset(st: Optional[SentinelState]
                         ) -> Optional[SentinelState]:
    """Restart the stall/explosion references at a precision-ladder stage
    boundary — the acceleration-history lesson (ops/accel.py) applied to
    the sentinel: a hot stage exits AT its noise floor, so its `best` is a
    bar the wider stage's residuals (which restart above it and decay at
    the operator's own rate) can take hundreds of sweeps to beat — carrying
    it across the cast would trip a false "stall" on a perfectly healthy
    polish. `best`/`first`/`since_best` restart; the verdict stays sticky
    (a failure in ANY stage is the solve's failure) and `count` keeps the
    cumulative watched-sweep total (sentinel_update captures `first` on
    the first finite residual after a reset, not on count == 0). No-op
    when off."""
    if st is None:
        return None
    inf = jnp.asarray(jnp.inf, _DT)
    return SentinelState(verdict=st.verdict, best=inf, first=inf,
                         since_best=jnp.int32(0), count=st.count)


def sentinel_update(st: Optional[SentinelState], residual, *,
                    config: Optional[SentinelConfig],
                    escaped=None) -> Optional[SentinelState]:
    """Watch one sweep's residual. `escaped` (a traced bool, or None) marks
    a non-finite residual as the solver's windowed-inversion escape rather
    than numerical divergence — the verdict the retry wrappers key on. The
    verdict is STICKY: once nonzero it never changes (the loop exits on the
    next cond evaluation anyway, and a sticky code survives the exit).
    No-op when off."""
    if st is None:
        return None
    r = jnp.asarray(residual).astype(_DT)
    finite = jnp.isfinite(r)
    # `first` captures the first FINITE residual since init/stage reset
    # (both leave it +inf) — the explosion reference.
    first = jnp.where(~jnp.isfinite(st.first) & finite, r, st.first)
    improved = finite & (r < st.best)
    best = jnp.where(improved, r, st.best)
    since = jnp.where(improved, 0, st.since_best + 1).astype(jnp.int32)

    bad = jnp.where(
        jnp.asarray(escaped if escaped is not None else False),
        jnp.int32(_ESCAPE), jnp.int32(_NAN))
    explode = finite & (r > jnp.asarray(config.explode_factor, _DT) * first)
    stall = since >= jnp.int32(config.stall_window)
    new = jnp.where(
        ~finite, bad,
        jnp.where(explode, jnp.int32(_EXPLODE),
                  jnp.where(stall, jnp.int32(_STALL), jnp.int32(_OK))))
    verdict = jnp.where(st.verdict != _OK, st.verdict, new)
    return SentinelState(verdict=verdict, best=best, first=first,
                         since_best=since, count=st.count + 1)


def sentinel_cond(st: Optional[SentinelState], base):
    """AND the healthy-verdict predicate into a loop condition. Returns
    `base` UNCHANGED when the sentinel is off — the off-path loop cond must
    trace to the identical expression."""
    if st is None:
        return base
    return base & (st.verdict == _OK)


# shard_map crossings: the state crosses the boundary as a flat tuple of
# leaves with explicit replicated out_specs, exactly like telemetry_leaves.
_N_LEAVES = 5


def sentinel_leaves(st: Optional[SentinelState]) -> tuple:
    """Flatten to a static-length tuple of arrays (empty when off)."""
    if st is None:
        return ()
    return (st.verdict, st.best, st.first, st.since_best, st.count)


def sentinel_from_leaves(leaves) -> Optional[SentinelState]:
    """Inverse of sentinel_leaves."""
    if not leaves:
        return None
    assert len(leaves) == _N_LEAVES
    return SentinelState(*leaves)


def verdict_name(verdict) -> str:
    """Host name of one verdict code (device_get's a device scalar)."""
    return VERDICT_NAMES[int(jax.device_get(verdict))]


def sentinel_summary(st: Optional[SentinelState]) -> Optional[dict]:
    """JSON-ready summary of one sentinel state — what rescue attempts and
    ledger events store. Batched states have no single verdict; index one
    scenario down first."""
    if st is None:
        return None
    verdict, best, first, since, count = (
        np.asarray(x) for x in jax.device_get(sentinel_leaves(st)))
    if verdict.ndim != 0:
        raise ValueError(
            "sentinel_summary reads ONE state; index a batched sentinel "
            f"(shape {verdict.shape}) down to one scenario first")
    return {
        "verdict": VERDICT_NAMES[int(verdict)],
        "best_residual": float(best) if np.isfinite(best) else None,
        "first_residual": float(first) if np.isfinite(first) else None,
        "since_best": int(since),
        "sweeps_watched": int(count),
    }


def host_verdict(history, config: Optional[SentinelConfig]) -> str:
    """The sentinel verdicts applied to a HOST-side residual history (the
    outer loops collect their per-round residuals as Python lists). Returns
    "" while healthy, else "nan" | "stall" | "explode" — the same taxonomy
    as the device states (escape is a device-loop concept and never fires
    here). No-op ("" always) when config is None."""
    if config is None or not history:
        return ""
    last = float(history[-1])
    if not math.isfinite(last):
        return "nan"
    finite = [float(h) for h in history if math.isfinite(float(h))]
    if not finite:
        return ""
    if last > config.explode_factor * finite[0]:
        return "explode"
    w = int(config.stall_window)
    if len(finite) > w and min(finite[-w:]) >= min(finite[:-w]):
        return "stall"
    return ""
