"""Krusell-Smith household solver: endogenous-grid-method policy iteration.

The reference (Krusell_Smith_EGM.m:128-209) runs a triple loop (4 states x
4 K-points x 100 k'-points) with ~6,400 interp1 calls per sweep. Here one
sweep is a single batched program: the Euler expectation is computed for all
(s, K, k') at once, the endogenous grid is inverted elementwise, and the
sort/mask/pchip-reinterpolate step runs as a vmapped masked kernel.

Known reference quirk (SURVEY.md §3.4): next-period prices and the next-period
policy slice are evaluated at K'' = ALM(ALM(K)) rather than at K' — the
computed K_prime_idx at Krusell_Smith_EGM.m:146 is never used, which marks the
double application as accidental. `double_alm=True` reproduces it;
the default False uses the economically correct single application (both
converge to ALM fixed points with R^2 ~ 1; the K grid snap usually makes them
identical anyway).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.progress import device_progress
from aiyagari_tpu.ops.interp import masked_pchip_interp
from aiyagari_tpu.solvers.ks_vfi import KSSolution, _alm_next_K_index
from aiyagari_tpu.utils.utility import crra_marginal, crra_marginal_inverse

__all__ = ["solve_ks_egm"]


@partial(jax.jit, static_argnames=("theta", "beta", "mu", "l_bar", "tol", "max_iter",
                                   "double_alm", "progress_every"))
def solve_ks_egm(k_opt_init, B, k_grid, K_grid, P, r_table, w_table, eps_by_state,
                 z_by_state, L_by_state, alpha: float, *, theta: float, beta: float,
                 mu: float, l_bar: float, delta: float, k_min: float, k_max: float,
                 tol: float, max_iter: int, double_alm: bool = False,
                 progress_every: int = 0) -> KSSolution:
    """EGM fixed point on the capital policy k_opt [ns, nK, nk] given ALM
    coefficients B. Convergence: absolute sup-norm on k_opt < tol
    (Krusell_Smith_EGM.m:204-206, tol 1e-6, <=10000 sweeps).
    """
    ns, nK, nk = k_opt_init.shape
    labor_endow = eps_by_state * l_bar + (1.0 - eps_by_state) * mu        # [ns]

    Kp_idx = _alm_next_K_index(B, K_grid, ns)                             # [ns, nK]
    Kp_val = K_grid[Kp_idx]

    # Aggregate index used for NEXT-period prices/policy: K' (correct) or
    # K'' = ALM(K') (reference). Computed per (s, K, s').
    zp_index = (jnp.arange(ns) % 2)                                       # z regime of s'
    if double_alm:
        from aiyagari_tpu.solvers.ks_vfi import alm_predict

        Kpp = alm_predict(B, Kp_val[:, :, None], zp_index[None, None, :])  # [ns, nK, ns']
        Kpp = jnp.clip(Kpp, K_grid[0], K_grid[-1])
        Knext_idx = jnp.argmin(
            jnp.abs(K_grid[None, None, None, :] - Kpp[..., None]), axis=-1
        ).astype(jnp.int32)                                               # [ns, nK, ns']
    else:
        Knext_idx = jnp.broadcast_to(Kp_idx[:, :, None], (ns, nK, ns))

    # Next-period prices at the chosen aggregate index (Krusell_Smith_EGM.m:173-175).
    r_next_tab = r_table[jnp.arange(ns)[None, None, :], Knext_idx]        # [ns, nK, ns']
    w_next_tab = w_table[jnp.arange(ns)[None, None, :], Knext_idx]

    r_cur = r_table  # [ns, nK] current-period prices (:150-151)
    w_cur = w_table

    def sweep(k_opt):
        def per_sK(s, K_i):
            # Expected marginal utility at each k' gridpoint (:155-184).
            def per_next(sp):
                rn = r_next_tab[s, K_i, sp]
                wn = w_next_tab[s, K_i, sp]
                # The reference interpolates the next-period policy at the
                # k' gridpoints themselves (pchip interp1 at :179) — an exact
                # identity, since queries sit on the knots. Use the policy
                # row directly instead of rebuilding slope tables per sweep.
                kp_next = k_opt[sp, Knext_idx[s, K_i, sp], :]
                resources_next = (1.0 + rn - delta) * k_grid + wn * labor_endow[sp]
                c_next = jnp.maximum(resources_next - kp_next, 1e-8)      # :181
                return P[s, sp] * (1.0 + rn - delta) * crra_marginal(c_next, theta)

            expected = jnp.sum(jax.vmap(per_next)(jnp.arange(ns)), axis=0)  # [nk]
            c = crra_marginal_inverse(beta * expected, theta)               # :187
            k_endo = (c + k_grid - w_cur[s, K_i] * labor_endow[s]) / (1.0 + r_cur[s, K_i] - delta)  # :188

            # Sort the endogenous grid, mask to [k_min, k_max], pchip back onto
            # the exogenous grid with nearest extrapolation, clamp (:192-198).
            valid = (k_endo >= k_min) & (k_endo <= k_max)
            x_masked = jnp.where(valid, k_endo, jnp.inf)
            order = jnp.argsort(x_masked)
            xs = x_masked[order]
            ys = k_grid[order]
            n_valid = jnp.sum(valid).astype(jnp.int32)
            out = masked_pchip_interp(xs, ys, jnp.maximum(n_valid, 2), k_grid)
            return jnp.clip(out, k_min, k_max)

        s_idx, K_idx = jnp.meshgrid(jnp.arange(ns), jnp.arange(nK), indexing="ij")
        new_flat = jax.vmap(per_sK)(s_idx.ravel(), K_idx.ravel())
        return new_flat.reshape(ns, nK, nk)

    def cond(carry):
        _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        k_opt, _, it = carry
        k_new = sweep(k_opt)
        dist = jnp.max(jnp.abs(k_new - k_opt))
        device_progress("ks_egm", it + 1, dist, every=progress_every)
        return k_new, dist, it + 1

    init = (k_opt_init, jnp.array(jnp.inf, k_opt_init.dtype), jnp.int32(0))
    k_opt, dist, it = jax.lax.while_loop(cond, body, init)
    return KSSolution(jnp.zeros_like(k_opt), k_opt, it, dist)
