"""Value-function-iteration solver: device-resident fixed point via
lax.while_loop, with optional Howard (policy-evaluation) acceleration.

The reference re-runs an interpreted double loop per sweep
(Aiyagari_VFI.m:65-90); here each sweep is one fused XLA program and the whole
fixed point stays on device — the host sees only the converged result.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.bellman import (
    bellman_step,
    bellman_step_labor,
    howard_eval_step,
    howard_eval_step_labor,
)

__all__ = ["VFISolution", "solve_aiyagari_vfi", "solve_aiyagari_vfi_labor"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class VFISolution:
    """Converged household solution on the grid. policy_l is all-ones for
    exogenous-labor models."""

    v: jax.Array              # [N, na]
    policy_idx: jax.Array     # [N, na] int32 argmax index into a_grid
    policy_k: jax.Array       # [N, na]
    policy_c: jax.Array       # [N, na]
    policy_l: jax.Array       # [N, na]
    iterations: jax.Array     # scalar int32
    distance: jax.Array       # scalar, final sup-norm


@partial(jax.jit, static_argnames=("sigma", "beta", "tol", "max_iter", "howard_steps", "block_size", "relative_tol"))
def solve_aiyagari_vfi(v_init, a_grid, s, P, r, w, *, sigma: float, beta: float,
                       tol: float, max_iter: int, howard_steps: int = 0,
                       block_size: int = 0, relative_tol: bool = False) -> VFISolution:
    """Iterate the Bellman operator to a sup-norm fixed point.

    Convergence: max|v_new - v| < tol, matching Aiyagari_VFI.m:85 (absolute
    sup-norm, tol 1e-5, <=1000 sweeps). howard_steps>0 inserts that many
    policy-evaluation sweeps after each improvement (not used by the reference
    for Aiyagari, exposed for the scaled-up runs).
    """

    def eval_sweeps(v, idx):
        if howard_steps <= 0:
            return v

        def body(v, _):
            return howard_eval_step(v, idx, a_grid, s, P, r, w, sigma=sigma, beta=beta), None

        v, _ = jax.lax.scan(body, v, None, length=howard_steps)
        return v

    def cond(carry):
        _, _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        v, idx, _, it = carry
        v_new, idx = bellman_step(v, a_grid, s, P, r, w, sigma=sigma, beta=beta, block_size=block_size)
        diff = jnp.abs(v_new - v)
        dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
        v_new = eval_sweeps(v_new, idx)
        return v_new, idx, dist, it + 1

    init = (
        v_init,
        jnp.zeros(v_init.shape, jnp.int32),
        jnp.array(jnp.inf, v_init.dtype),
        jnp.int32(0),
    )
    v, idx, dist, it = jax.lax.while_loop(cond, body, init)
    policy_k = a_grid[idx]
    policy_c = (1.0 + r) * a_grid[None, :] + w * s[:, None] - policy_k
    return VFISolution(v, idx, policy_k, policy_c, jnp.ones_like(policy_k), it, dist)


@partial(jax.jit, static_argnames=("sigma", "beta", "psi", "eta", "tol", "max_iter", "howard_steps", "relative_tol"))
def solve_aiyagari_vfi_labor(v_init, a_grid, labor_grid, s, P, r, w, *, sigma: float,
                             beta: float, psi: float, eta: float, tol: float,
                             max_iter: int, howard_steps: int = 0,
                             relative_tol: bool = False) -> VFISolution:
    """VFI with the joint (labor x a') discrete choice
    (Aiyagari_Endogenous_Labor_VFI.m:64-122)."""

    def eval_sweeps(v, a_idx, l_idx):
        if howard_steps <= 0:
            return v

        def body(v, _):
            return howard_eval_step_labor(
                v, a_idx, l_idx, a_grid, labor_grid, s, P, r, w,
                sigma=sigma, beta=beta, psi=psi, eta=eta,
            ), None

        v, _ = jax.lax.scan(body, v, None, length=howard_steps)
        return v

    def cond(carry):
        return (carry[3] >= tol) & (carry[4] < max_iter)

    def body(carry):
        v, a_idx, l_idx, _, it = carry
        v_new, a_idx, l_idx = bellman_step_labor(
            v, a_grid, labor_grid, s, P, r, w, sigma=sigma, beta=beta, psi=psi, eta=eta
        )
        diff = jnp.abs(v_new - v)
        dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
        v_new = eval_sweeps(v_new, a_idx, l_idx)
        return v_new, a_idx, l_idx, dist, it + 1

    zeros_i = jnp.zeros(v_init.shape, jnp.int32)
    init = (v_init, zeros_i, zeros_i, jnp.array(jnp.inf, v_init.dtype), jnp.int32(0))
    v, a_idx, l_idx, dist, it = jax.lax.while_loop(cond, body, init)
    policy_k = a_grid[a_idx]
    policy_l = labor_grid[l_idx]
    policy_c = (1.0 + r) * a_grid[None, :] + w * s[:, None] * policy_l - policy_k
    return VFISolution(v, a_idx, policy_k, policy_c, policy_l, it, dist)
