"""Value-function-iteration solver: device-resident fixed point via
lax.while_loop, with optional Howard (policy-evaluation) acceleration.

The reference re-runs an interpreted double loop per sweep
(Aiyagari_VFI.m:65-90); here each sweep is one fused XLA program and the whole
fixed point stays on device — the host sees only the converged result.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.faults import poison_iterate
from aiyagari_tpu.diagnostics.progress import device_progress
from aiyagari_tpu.diagnostics.sentinel import (
    sentinel_cond,
    sentinel_init,
    sentinel_stage_reset,
    sentinel_update,
)
from aiyagari_tpu.diagnostics.telemetry import telemetry_init, telemetry_record
from aiyagari_tpu.ops.precision import matmul_precision_of, plan_stages
from aiyagari_tpu.solvers._stopping import effective_tolerance
from aiyagari_tpu.ops.bellman import (
    expectation,
    bellman_step,
    bellman_step_labor,
    bellman_step_labor_precomputed,
    bellman_step_precomputed,
    choice_utility_tensor,
    labor_choice_utility_tensor,
    howard_eval_step,
    howard_eval_step_labor,
)

__all__ = [
    "VFISolution",
    "solve_aiyagari_vfi",
    "solve_aiyagari_vfi_labor",
    "solve_aiyagari_vfi_continuous",
    "solve_aiyagari_vfi_multiscale",
    "solve_aiyagari_vfi_egm_warmstart",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class VFISolution:
    """Converged household solution on the grid. policy_l is all-ones for
    exogenous-labor models."""

    v: jax.Array              # [N, na]
    policy_idx: jax.Array     # [N, na] int32 argmax index into a_grid
    policy_k: jax.Array       # [N, na]
    policy_c: jax.Array       # [N, na]
    policy_l: jax.Array       # [N, na]
    iterations: jax.Array     # scalar int32
    distance: jax.Array       # scalar, final sup-norm
    # The tolerance the stopping rule actually applied: == tol for the
    # discrete solvers, max(tol, noise floor) when the continuous solver's
    # ulp-noise floor was engaged (noise_floor_ulp). Convergence checks
    # should compare distance against THIS, not tol
    # (cf. EGMSolution.tol_effective).
    tol_effective: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0.0))
    # Total policy-EVALUATION sweeps executed (pre-warm burst + one
    # howard_steps burst per improvement round + post-exit polish), for the
    # continuous solver only (0 elsewhere). `iterations` counts improvement
    # ROUNDS; the roofline cost model (diagnostics/roofline.vfi_slab_cost)
    # needs both, since an EGM-warm-started solve is almost all evaluation.
    eval_sweeps: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0, jnp.int32))
    # Mixed-precision ladder telemetry (ops/precision.py; 0 when no ladder
    # ran): sweeps executed in the hot (pre-polish) stages, and the value
    # residual at which the dtype switch fired (cf. EGMSolution).
    hot_iterations: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0, jnp.int32))
    switch_distance: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0.0))
    # Device-resident flight record (diagnostics/telemetry.py): per-sweep
    # value residuals + stage dtypes when SolverConfig.telemetry is set;
    # None (an empty pytree leaf) when the recorder was compiled out.
    telemetry: object = None
    # Failure-sentinel state (diagnostics/sentinel.py) with the structured
    # early-exit verdict, when SolverConfig.sentinel is set; None when the
    # sentinel was compiled out.
    sentinel: object = None


def _solve_aiyagari_vfi_impl(v_init, a_grid, s, P, r, w, sigma, beta, *,
                             tol: float, max_iter: int, howard_steps: int = 0,
                             block_size: int = 0, relative_tol: bool = False,
                             use_pallas: bool = False, progress_every: int = 0,
                             noise_floor_ulp: float = 0.0,
                             ladder=None, telemetry=None, sentinel=None,
                             faults=None) -> VFISolution:
    stages = plan_stages(ladder, v_init.dtype, noise_floor_ulp)
    na = v_init.shape[1]
    dense = block_size <= 0 or block_size >= na

    def run_stage(spec, v0, idx0, it0, tele_in, sent_in):
        dt = jnp.dtype(spec.dtype)
        # None = backend default; the ladder's hot stages may relax the
        # expectation contraction (bf16 MXU on TPU), the final/no-ladder
        # stage keeps the historical HIGHEST pin.
        prec = (matmul_precision_of(spec.matmul_precision)
                or jax.lax.Precision.DEFAULT)
        ag, sd, Pd = a_grid.astype(dt), s.astype(dt), P.astype(dt)
        rd, wd = jnp.asarray(r).astype(dt), jnp.asarray(w).astype(dt)
        sig, bet = jnp.asarray(sigma).astype(dt), jnp.asarray(beta).astype(dt)
        tol_c = jnp.asarray(tol, dt)
        # Per-stage sentinel reference restart: a hot stage's noise-floor
        # best must not stall the polish (sentinel_stage_reset docstring).
        sent_in = sentinel_stage_reset(sent_in)

        def eval_sweeps(v, idx):
            if howard_steps <= 0:
                return v

            def body(v, _):
                return howard_eval_step(v, idx, ag, sd, Pd, rd, wd,
                                        sigma=sig, beta=bet,
                                        precision=prec), None

            v, _ = jax.lax.scan(body, v, None, length=howard_steps)
            return v

        def cond(carry):
            _, _, dist, it, tol_eff, _, sent = carry
            return sentinel_cond(sent, (dist >= tol_eff) & (it < max_iter))

        # Dense path: the masked choice-utility tensor is loop-invariant, so
        # compute it once here (per ladder stage: loop-invariant but
        # dtype-dependent — the hot stage's HALF-WIDTH U tensor is exactly
        # the HBM-bytes saving the ladder exists for) and keep only
        # EV + add + max inside the while_loop (choice_utility_tensor
        # docstring). Blocked/Pallas paths keep the fused per-sweep form —
        # at their scales the [N, na, na'] tensor is the thing that must NOT
        # be materialized.
        U = (choice_utility_tensor(ag, sd, rd, wd, sigma=sig, dtype=dt)
             if dense and not use_pallas else None)

        def body(carry):
            v, idx, _, it, _, tele, sent = carry
            if U is not None:
                v_new, idx = bellman_step_precomputed(v, U, Pd, beta=bet,
                                                      precision=prec)
            else:
                v_new, idx = bellman_step(v, ag, sd, Pd, rd, wd, sigma=sig,
                                          beta=bet, block_size=block_size,
                                          use_pallas=use_pallas,
                                          precision=prec)
            v_new = poison_iterate(faults, v_new, it)
            diff = jnp.abs(v_new - v)
            dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
            tol_eff = effective_tolerance(
                tol_c, jnp.max(jnp.abs(v_new)),
                noise_floor_ulp=spec.noise_floor_ulp,
                relative_tol=relative_tol, dtype=dt)
            device_progress("aiyagari_vfi", it + 1, dist, every=progress_every)
            tele = telemetry_record(tele, dist)
            sent = sentinel_update(sent, dist, config=sentinel)
            v_new = eval_sweeps(v_new, idx)
            return v_new, idx, dist, it + 1, tol_eff, tele, sent

        init = (v0.astype(dt), idx0, jnp.array(jnp.inf, dt), it0, tol_c,
                tele_in, sent_in)
        return jax.lax.while_loop(cond, body, init)

    v, idx = v_init, jnp.zeros(v_init.shape, jnp.int32)
    it = jnp.int32(0)
    hot_it = jnp.int32(0)
    switch_dist = jnp.array(0.0, jnp.dtype(stages[-1].dtype))
    tele = telemetry_init(telemetry)
    sent = sentinel_init(sentinel)
    dist = tol_eff = None
    for spec in stages:
        v, idx, dist, it, tol_eff, tele, sent = run_stage(spec, v, idx, it,
                                                          tele, sent)
        if not spec.is_final:
            hot_it = it
            switch_dist = dist.astype(switch_dist.dtype)
    dt_f = jnp.dtype(stages[-1].dtype)
    ag_f = a_grid.astype(dt_f)
    policy_k = ag_f[idx]
    policy_c = ((1.0 + jnp.asarray(r).astype(dt_f)) * ag_f[None, :]
                + jnp.asarray(w).astype(dt_f) * s.astype(dt_f)[:, None]
                - policy_k)
    return VFISolution(v, idx, policy_k, policy_c, jnp.ones_like(policy_k), it,
                       dist, tol_eff, hot_iterations=hot_it,
                       switch_distance=switch_dist, telemetry=tele,
                       sentinel=sent)


_VFI_STATIC = ("tol", "max_iter", "howard_steps", "block_size",
               "relative_tol", "use_pallas", "progress_every",
               "noise_floor_ulp", "ladder", "telemetry", "sentinel",
               "faults")
# Default program: sigma/beta are TRACED operands, so (a) a batch of scenarios
# differing only in preferences compiles once, and (b) the whole solve vmaps
# over (r, sigma, beta, ...) — the batched-GE requirement. The Pallas route
# alone keeps them static (the fused kernel bakes sigma in).
_solve_vfi_traced = partial(jax.jit, static_argnames=_VFI_STATIC)(
    _solve_aiyagari_vfi_impl)
_solve_vfi_static_prefs = partial(
    jax.jit, static_argnames=_VFI_STATIC + ("sigma", "beta"))(
    _solve_aiyagari_vfi_impl)


def solve_aiyagari_vfi(v_init, a_grid, s, P, r, w, *, sigma, beta,
                       tol: float, max_iter: int, howard_steps: int = 0,
                       block_size: int = 0, relative_tol: bool = False,
                       use_pallas: bool = False, progress_every: int = 0,
                       noise_floor_ulp: float = 0.0,
                       ladder=None, telemetry=None, sentinel=None,
                       faults=None) -> VFISolution:
    """Iterate the Bellman operator to a sup-norm fixed point.

    Convergence: max|v_new - v| < tol, matching Aiyagari_VFI.m:85 (absolute
    sup-norm, tol 1e-5, <=1000 sweeps). howard_steps>0 inserts that many
    policy-evaluation sweeps after each improvement (not used by the reference
    for Aiyagari, exposed for the scaled-up runs). progress_every>0 emits an
    in-jit telemetry record every that-many sweeps (diagnostics.progress;
    0 = off, zero cost).

    sigma and beta are traced operands (jit compiles ONE program for any
    preference values, and the solve vmaps over batched (r, sigma, beta) —
    equilibrium/batched.py builds its excess-demand kernel on exactly this).
    Exception: use_pallas=True requires concrete Python floats for them, since
    the fused Pallas kernel specializes on sigma at compile time.

    ladder (a PrecisionLadderConfig, static) opts into the mixed-precision
    solve ladder: the early Bellman/Howard sweeps run in the hot dtype
    against a HALF-WIDTH precomputed choice-utility tensor (the U read is
    the dense sweep's dominant HBM term — diagnostics/roofline.
    vfi_sweep_cost), switch at max(tol, switch_ulp * eps * max|v|), then
    the full-precision loop polishes to the reference criterion
    (solvers/egm.solve_aiyagari_egm's ladder semantics, applied to the
    value iterate). noise_floor_ulp is the f32 stopping-rule floor of the
    FINAL stage (solvers/_stopping.effective_tolerance; 0 = strict).
    Incompatible with use_pallas (the fused kernel bakes one dtype in).
    """
    if ladder is not None and use_pallas:
        raise ValueError(
            "the mixed-precision ladder cannot route through the fused "
            "Pallas Bellman kernel (it specializes one dtype at compile "
            "time); drop use_pallas or ladder")
    fn = _solve_vfi_static_prefs if use_pallas else _solve_vfi_traced
    return fn(v_init, a_grid, s, P, r, w, sigma, beta, tol=tol,
              max_iter=max_iter, howard_steps=howard_steps,
              block_size=block_size, relative_tol=relative_tol,
              use_pallas=use_pallas, progress_every=progress_every,
              noise_floor_ulp=noise_floor_ulp, ladder=ladder,
              telemetry=telemetry, sentinel=sentinel, faults=faults)


@partial(jax.jit, static_argnames=("sigma", "beta", "tol", "max_iter", "howard_steps",
                                   "golden_iters", "relative_tol", "grid_power",
                                   "slab", "noise_floor_ulp"))
def solve_aiyagari_vfi_continuous(v_init, a_grid, s, P, r, w, amin, *, sigma: float,
                                  beta: float, tol: float, max_iter: int,
                                  howard_steps: int = 20, golden_iters: int = 48,
                                  relative_tol: bool = False,
                                  grid_power: float = 0.0,
                                  slab: bool | None = None,
                                  noise_floor_ulp: float = 0.0,
                                  idx_init=None) -> VFISolution:
    """Scalable VFI: coarse-to-fine maximization of u(coh - a'_j) + EV_j over
    grid *indices* j (ops/golden.unimodal_argmax_index), followed by one
    continuous golden-section refinement of the converged policy within its
    bracketing cells — O(na log na) per sweep instead of the dense search's
    O(na^2), so it scales to grids 1000x the reference's 400 points.

    Why index search and not continuous golden section inside the loop: near
    the top of the grid the objective is extremely flat (u'(c) ~ c^-sigma at
    c ~ O(100) is below f32 resolution of a value ~O(40)), so a continuous
    maximizer jitters by whole grid cells from sweep to sweep and the value
    iteration stalls around 1e-2 — measured on this image at grid 400, f32.
    Grid candidates, ranked by direct value comparison at every level of the
    coarse-to-fine search, behave like the dense discrete argmax (value error
    bounded at evaluation-rounding level), so this path converges to the
    dense search's fixed point (pinned by TestContinuousVFI) in f32 and f64
    alike.

    This is the same solver family as the Krusell-Smith Howard VFI
    (solvers/ks_vfi.py, replacing Krusell_Smith_VFI.m:141-204's fminbnd).
    Howard evaluation sweeps amortize each improvement. golden_iters > 0
    enables the final in-cell continuous refine of the returned policy
    (policy_k/policy_c move off-grid; v and policy_idx stay the discrete
    fixed point); golden_iters = 0 returns the pure grid solution.

    slab=None auto-selects the monotone-policy SLAB improvement/evaluation
    above 4,096 points (block-DMA dense argmax + one-hot Howard
    contraction — no EV element gathers; BENCHMARKS.md round 3); True or
    False forces a route (TestContinuousVFI pins slab == local-window).

    idx_init (optional [N, na] int32) is a cross-method POLICY warm start:
    the policy-index guess is first made value-consistent by a pure
    policy-evaluation fixed point (no improvement work), and the Howard loop
    then starts at (V^pi0, pi0) instead of (v_init, all-zeros). With a guess
    from a converged EGM solve the improvement rounds collapse to the 1-3
    verification rounds policy iteration needs near its fixed point — the
    cold solve's 17-31 rounds at fine grids are exactly the walk this skips
    (BENCHMARKS.md round 5). The policy-repeat stopping test arms
    immediately under a warm start (the initial policy is a real iterate,
    not the all-zeros sentinel the cold arming delay protects against).

    noise_floor_ulp > 0 widens the absolute stopping tolerance to
    max(tol, noise_floor_ulp * eps(dtype) * max|v|) — the VALUE criterion's
    own f32 rounding band, exactly the EGM solvers' noise_floor_ulp
    semantics (solvers/egm.solve_aiyagari_egm docstring). Why it exists
    here too: at [7, 400k] f32 the value sup-norm wanders at 1.2-4.9e-4
    (~24 ulp of values O(100)) forever while tol=1e-5 never fires, and the
    policy-stability stop cannot catch every flat-top wobble pattern at
    2.8M points — the un-floored loop ran to max_iter inside one device
    call until the remote transport killed the TPU worker (round 4,
    BENCHMARKS.md). The applied tolerance is returned as
    VFISolution.tol_effective; convergence checks must use it.
    """
    from aiyagari_tpu.ops.golden import golden_section_max, unimodal_argmax_index
    from aiyagari_tpu.ops.interp import bucket_index, power_bucket_index
    from aiyagari_tpu.utils.utility import crra_utility as _u

    N, na = v_init.shape
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]          # [N, na]

    def locate(q):
        # grid_power > 0 means a_grid is power-spaced: O(1) closed-form
        # locator instead of a search (ops/interp.power_bucket_index).
        if grid_power > 0.0:
            return power_bucket_index(a_grid, q, a_grid[0], a_grid[-1], grid_power)
        return bucket_index(a_grid, q)

    # Dtype- and sigma-aware consumption floor. Two constraints: it must not
    # underflow to 0.0 (u(0) = -inf), and u(c_floor) = c_floor^(1-sigma)/
    # (1-sigma) must stay FINITE — an infinite penalty at a state with no
    # feasible choice (possible when the grid extends below the borrowing
    # limit) makes v = -inf there and the convergence distance NaN. Pick the
    # largest floor that bounds the penalty near the dtype max.
    # (For 1 < sigma < 2 the overflow bound itself underflows past tiny —
    # e.g. sigma=1.5, f32: 3e-77 -> 0.0 — so floor it at tiny as well.)
    fin = jnp.finfo(v_init.dtype)
    if sigma > 1.0:
        c_floor = max(4.0 * float(fin.max) ** (-1.0 / (sigma - 1.0)),
                      float(fin.tiny))
    else:
        c_floor = float(fin.tiny)

    # Feasible choice indices [lo_idx, hi_idx]: lo_idx is the smallest j with
    # a_grid[j] >= amin (the grid may extend below the borrowing limit);
    # hi_idx the largest j with a_grid[j] < coh (c > 0), so the search never
    # ranks points inside the clamped-consumption penalty region (where the
    # objective turns non-unimodal). Computed once per solve.
    lo_idx = jnp.minimum(jnp.sum(a_grid < amin), na - 1).astype(jnp.int32)
    loc = locate(coh)                                            # [N, na] in [0, na-2]
    hi_idx = jnp.where(
        a_grid[loc + 1] < coh, loc + 1,
        jnp.where(a_grid[loc] < coh, loc, jnp.maximum(loc - 1, 0)),
    ).astype(jnp.int32)
    hi_idx = jnp.maximum(hi_idx, lo_idx)

    def choice_value(EV, j):
        c = jnp.maximum(coh - a_grid[j], c_floor)
        return _u(c, sigma) + jnp.take_along_axis(EV, j, axis=1)

    # Dense re-scan window around the binary-search result: absorbs the small
    # non-unimodality the discrete upper envelope introduces near kinks (the
    # search needs unimodal f; Tv on a grid is only concave up to cell-level
    # envelope error). 17 extra batched evaluations per improvement.
    _W = 8
    # Local-improvement half-window: between improvement rounds (and after a
    # multigrid prolongation) the discrete policy drifts a few cells, so a
    # windowed argmax around the previous policy needs (2*_LW+1) objective
    # evaluations instead of the global coarse-to-fine search's ~160 — each
    # evaluation is a [N, na] EV element gather, the measured per-round
    # bottleneck of this solver at fine grids (BENCHMARKS.md round 1:
    # ~0.9 s/round at [7, 40k], gather-bound).
    _LW = 24

    def improve_global(EV):
        f = lambda j: choice_value(EV, j)
        idx0 = unimodal_argmax_index(f, hi_idx, na, lo_idx=lo_idx)
        offs = jnp.arange(-_W, _W + 1, dtype=jnp.int32)
        cand = jnp.clip(idx0[:, :, None] + offs, lo_idx, hi_idx[:, :, None])  # [N, na, 2W+1]
        vals = jax.vmap(f, in_axes=2, out_axes=2)(cand)
        return jnp.take_along_axis(
            cand, jnp.argmax(vals, axis=2)[:, :, None], axis=2
        )[:, :, 0]

    def improve_local_window(EV, idx_prev):
        # Small-grid route: per-point +/-_LW candidate window around the
        # previous policy (49 EV element-gathers per point — cheap at these
        # sizes). Returns (best, escalate).
        f = lambda j: choice_value(EV, j)
        offs = jnp.arange(-_LW, _LW + 1, dtype=jnp.int32)
        cand = jnp.clip(idx_prev[:, :, None] + offs, lo_idx, hi_idx[:, :, None])
        vals = jax.vmap(f, in_axes=2, out_axes=2)(cand)
        best = jnp.take_along_axis(
            cand, jnp.argmax(vals, axis=2)[:, :, None], axis=2
        )[:, :, 0]
        # A maximizer pinned to a window edge that is not a true bound means
        # the drift may exceed the window — fall back to the global search
        # for this round. "Pinned" requires the edge to STRICTLY beat its
        # inward neighbor: in the f32 flat-top regime whole windows tie
        # exactly and argmax's first-max rule lands on the edge offset, which
        # would otherwise escalate every flat round to the global search.
        # The all-zeros initial policy hits the lo edge with a strict
        # gradient on round one, so cold starts transparently go global.
        at_lo = ((best == cand[:, :, 0]) & (cand[:, :, 0] > lo_idx)
                 & (vals[:, :, 0] > vals[:, :, 1]))
        at_hi = ((best == cand[:, :, -1]) & (cand[:, :, -1] < hi_idx)
                 & (vals[:, :, -1] > vals[:, :, -2]))
        return best, jnp.any(at_lo | at_hi)

    # Monotone-policy slab argmax (SURVEY.md §7.3's divide-and-conquer,
    # in its TPU-batched form): the optimal index is nondecreasing in
    # assets, so a block of _SQ consecutive queries has ALL its candidates
    # inside one contiguous EV slab around the block's previous-policy
    # span (span <= density ratio * _SQ + drift; the slab's >=_KB cells of
    # margin each side covers 10x the old +/-_LW drift bound). The slab is
    # fetched as _MW KB-granular contiguous blocks — block DMA, the
    # windowed-EGM pattern — and ALL slab positions are evaluated densely:
    # more FLOPs than the 49-candidate window, but pure VPU broadcast
    # work instead of 49 EV element-gathers per point, which were the
    # measured per-round bottleneck at fine grids (~0.45 s/round at
    # [7, 40k]; BENCHMARKS.md round 1).
    _SQ, _KB, _MW = 256, 256, 6
    _SLAB = _KB * _MW
    nkb = -(-na // _KB)
    use_slab = (na > 4096 if slab is None else bool(slab)) and nkb >= _MW

    _CB = 16              # query blocks per chunk of the slab evaluation
    nb_s = -(-na // _SQ)
    nT = -(-nb_s // _CB)
    nbp = nT * _CB
    padk_s = nkb * _KB - na

    def _slab_geometry(idx_anchor):
        """KB-granular slab starts per query block from an anchoring policy:
        ab[n, b] positions block b's _SLAB-cell candidate slab one knot
        block below the block's first anchor index (clamped). Shared by the
        improvement argmax and the Howard evaluation contraction."""
        idxp_pad = jnp.pad(idx_anchor, ((0, 0), (0, nbp * _SQ - na)),
                           mode="edge")
        # Clamp the anchor into the feasible index range: an anchor below
        # lo_idx (the all-zeros init on a grid extending below the
        # borrowing limit) would position a slab with NO feasible cell —
        # all--inf values whose tie-argmin silently returns an infeasible
        # index with no escalation (the clip-based local window could
        # never do that). Anchored at lo_idx, the slab always contains a
        # feasible position.
        first = jnp.clip(idxp_pad[:, :: _SQ], lo_idx, na - 1)    # [N, nbp]
        ab = jnp.clip((first - _KB) // _KB, 0, nkb - _MW)        # [N, nbp]
        return idxp_pad, ab

    def _slab_fetch(Xp, ab_chunk):
        """[N, _CB, _SLAB] slab values: _MW contiguous _KB-blocks per query
        block via a row-granular take_along_axis — block DMA, the
        windowed-EGM gather pattern (a vmapped dynamic_slice here lowered
        to a ~1.4 ms/block serial form under lax.map; measured)."""
        blk = ab_chunk[:, :, None] + jnp.arange(_MW)[None, None, :]
        cb = ab_chunk.shape[1]
        rows = jnp.take_along_axis(
            Xp.reshape(N, nkb, _KB),
            blk.reshape(N, cb * _MW)[:, :, None], axis=1)
        return rows.reshape(N, cb, _SLAB)

    def _slab_avals(jglob):
        if grid_power > 0.0:
            # Analytic slab of grid values — no gather at all.
            return a_grid[0] + (a_grid[-1] - a_grid[0]) * (
                jglob.astype(v_init.dtype) / (na - 1)) ** grid_power
        a_pad = jnp.concatenate(
            [a_grid, jnp.full((padk_s,), jnp.inf, a_grid.dtype)])
        return a_pad[jglob]

    def improve_slab(EV, idx_prev):
        # lax.map over chunks of _CB blocks: the full [N, nb, _SQ, _SLAB]
        # candidate tensor is ~30 GB at 40k points (it has multiple
        # consumers — max, tie-argmin, edge comparisons — so XLA
        # materializes it and the compile OOMs); per chunk it is ~176 MB.
        neg_inf = jnp.array(-jnp.inf, v_init.dtype)
        EVp = jnp.concatenate(
            [EV, jnp.full((N, padk_s), neg_inf, EV.dtype)], axis=1)
        joff = jnp.arange(_SLAB, dtype=jnp.int32)
        idxp_pad, ab_all = _slab_geometry(idx_prev)
        cohp = jnp.pad(coh, ((0, 0), (0, nbp * _SQ - na)), mode="edge")
        hip = jnp.pad(hi_idx, ((0, 0), (0, nbp * _SQ - na)), mode="edge")

        def chunk(t):
            q0 = t * _CB * _SQ
            ab = jax.lax.dynamic_slice_in_dim(ab_all, t * _CB, _CB, axis=1)
            seg = _slab_fetch(EVp, ab)                           # [N,_CB,_SLAB]
            jglob = ab[:, :, None] * _KB + joff[None, None, :]
            a_vals = _slab_avals(jglob)
            cut = lambda x: jax.lax.dynamic_slice_in_dim(
                x, q0, _CB * _SQ, axis=1).reshape(N, _CB, _SQ)
            cohb, hib, idxp_b = cut(cohp), cut(hip), cut(idxp_pad)
            c = jnp.maximum(cohb[..., None] - a_vals[:, :, None, :], c_floor)
            vals = _u(c, sigma) + seg[:, :, None, :]     # [N,_CB,_SQ,_SLAB]
            feas = (jglob[:, :, None, :] >= lo_idx) & \
                   (jglob[:, :, None, :] <= hib[..., None]) & \
                   (jglob[:, :, None, :] < na)
            vals = jnp.where(feas, vals, neg_inf)
            # Argmax with ties broken TOWARD the previous policy, not
            # first-max: in the f32 flat-top regime whole slab stretches
            # tie exactly, and a leftmost-tie rule would slide the policy
            # to the slab edge every round — the policy-repeat stop then
            # never fires and the loop burns max_iter rounds (and a
            # multi-minute single-kernel execution wedges this image's TPU
            # worker). Preferring the tied candidate closest to idx_prev
            # makes the policy STATIONARY once the value ties stabilize.
            vmax = jnp.max(vals, axis=3, keepdims=True)
            far = jnp.int32(2 ** 30)
            dist_j = jnp.abs(jglob[:, :, None, :] - idxp_b[..., None])
            jloc = jnp.argmin(
                jnp.where(vals >= vmax, dist_j, far),
                axis=3).astype(jnp.int32)                        # [N,_CB,_SQ]
            best = ab[..., None] * _KB + jloc
            # Same edge-pin escalation contract as the local window: a
            # strict maximizer at a slab edge that is not a true bound
            # means the drift exceeded the slab. (The slab always contains
            # the block's previous policy and a feasible index, so ties at
            # an all--inf edge cannot fire the STRICT comparison.)
            e0 = vals[..., 0] > vals[..., 1]
            e1 = vals[..., -1] > vals[..., -2]
            at_lo = (jloc == 0) & (jglob[:, :, :1] > lo_idx) & e0
            at_hi = (jloc == _SLAB - 1) & (jglob[:, :, -1:] < hib) & e1
            return best, jnp.any(at_lo | at_hi)

        best_c, esc_c = jax.lax.map(chunk, jnp.arange(nT))  # [nT, N, _CB, _SQ]
        best = jnp.moveaxis(best_c, 0, 1).reshape(N, nbp * _SQ)[:, :na]
        return best, jnp.any(esc_c)

    def improve(v, idx_prev):
        EV = expectation(P, v, beta)   # hoisted: one per improvement
        if use_slab:
            best, escalate = improve_slab(EV, idx_prev)
        else:
            best, escalate = improve_local_window(EV, idx_prev)
        return jax.lax.cond(
            escalate,
            lambda: improve_global(EV),
            lambda: best,
        )

    def evaluate(v, idx):
        # Howard policy evaluation: the policy is fixed across sweeps, at
        # exact grid points — no interpolation, just an expectation matmul
        # and the policy-indexed EV read per sweep.
        u_pol = _u(jnp.maximum(coh - a_grid[idx], c_floor), sigma)

        def sweep_gather(v, _):
            EV = expectation(P, v, beta)
            return u_pol + jnp.take_along_axis(EV, idx, axis=1), None

        def run_gather(v):
            v, _ = jax.lax.scan(sweep_gather, v, None,
                                length=max(howard_steps, 1))
            return v

        if not use_slab:
            return run_gather(v)

        # Fine-grid route: the per-sweep take_along_axis is an [N, na]
        # ELEMENT gather — the measured bottleneck of the evaluation burst
        # (~2 ms at [7, 40k], x howard_steps x rounds). Replace it with the
        # slab one-hot contraction: fetch each query block's EV slab as
        # contiguous knot blocks (block DMA) and reduce
        # sum(where(jglob == idx, seg, 0)) — bitwise equal to the gather
        # (adding exact zeros), but pure VPU broadcast work. The slab is
        # re-anchored at THIS policy, whose own block span always satisfies
        # the slab's lower bound (slab start <= block-first index <= idx);
        # only an upper-bound violation (a >1,024-cell policy jump inside
        # one 256-query block) is possible — checked once per round, with
        # the gather route as the lax.cond fallback so correctness never
        # depends on the span assumption.
        idxp_pad, ab_all = _slab_geometry(idx)
        idxb = idxp_pad.reshape(N, nbp, _SQ)
        joff = jnp.arange(_SLAB, dtype=jnp.int32)
        # BOTH bounds: within-block monotonicity of idx is not guaranteed
        # (improve_global on an f32 tie plateau can jump non-monotonically),
        # so an index below its block's slab start is as reachable as one
        # above its end — either would make the contraction silently drop
        # the continuation value.
        slab_start = ab_all[:, :, None] * _KB
        in_slab = jnp.all((idxb >= slab_start) & (idxb < slab_start + _SLAB))

        def sweep_slab(v, _):
            EV = expectation(P, v, beta)
            EVp = jnp.concatenate(
                [EV, jnp.zeros((N, padk_s), EV.dtype)], axis=1)

            # Chunked like improve_slab, and for the same reason: the
            # un-chunked [N, nbp, _SQ, _SLAB] one-hot broadcast is ~17 GB
            # at 400k points — it CRASHED the TPU worker (HBM OOM) the
            # first time the north-star scale ran this solver; per chunk
            # it is ~176 MB.
            def chunk(t):
                ab = jax.lax.dynamic_slice_in_dim(ab_all, t * _CB, _CB,
                                                  axis=1)
                seg = _slab_fetch(EVp, ab)                   # [N,_CB,_SLAB]
                jg = ab[:, :, None] * _KB + joff[None, None, :]
                idxc = jax.lax.dynamic_slice_in_dim(
                    idxb, t * _CB, _CB, axis=1)              # [N,_CB,_SQ]
                return jnp.sum(
                    jnp.where(jg[:, :, None, :] == idxc[..., None],
                              seg[:, :, None, :], 0.0), axis=3)

            g = jax.lax.map(chunk, jnp.arange(nT))           # [nT,N,_CB,_SQ]
            g = jnp.moveaxis(g, 0, 1).reshape(N, nbp * _SQ)[:, :na]
            return u_pol + g, None

        def run_slab(v):
            v, _ = jax.lax.scan(sweep_slab, v, None,
                                length=max(howard_steps, 1))
            return v

        return jax.lax.cond(in_slab, run_slab, run_gather, v)

    # The f32 ulp-noise stopping floor (the EGM solvers' noise_floor_ulp,
    # solvers/egm.solve_aiyagari_egm docstring, applied to the VALUE
    # criterion): at fine grids the value iterate reaches its fixed point
    # and then wanders in the rounding band of the sup-norm — measured
    # 1.2-4.9e-4 at [7, 400k] f32 (values O(100): ~24 ulp), with absolute
    # tol 1e-5 UNREACHABLE there; the un-floored loop ground to max_iter
    # inside one device call until the remote transport killed the worker.
    tol_c = jnp.asarray(tol, v_init.dtype)

    def _tol_eff_of(v_new):
        return effective_tolerance(
            tol_c, jnp.max(jnp.abs(v_new)), noise_floor_ulp=noise_floor_ulp,
            relative_tol=relative_tol, dtype=v_init.dtype)

    def _eval_fixed_point(v0, idx_fix, d0, max_calls: int):
        """Pure policy evaluation iterated to the value stopping rule with
        the policy held fixed: each call is one howard_steps-sweep burst, so
        the per-call contraction is ~beta^howard_steps. Shared by the warm
        pre-evaluation (making an idx_init value-consistent) and the
        policy-repeat polish. Returns (v, dist, calls, tol_eff)."""

        def c_(c):
            _, d, k, te = c
            return (d >= te) & (k < jnp.int32(max_calls))

        def b_(c):
            vv, _, k, _ = c
            v2 = evaluate(vv, idx_fix)
            diff = jnp.abs(v2 - vv)
            d = (jnp.max(diff / (jnp.abs(vv) + 1e-10)) if relative_tol
                 else jnp.max(diff))
            return v2, d, k + 1, _tol_eff_of(v2)

        return jax.lax.while_loop(c_, b_, (v0, d0, jnp.int32(0), tol_c))

    warm = idx_init is not None
    if warm:
        idx0 = jnp.clip(idx_init.astype(jnp.int32), lo_idx, hi_idx)
        v_start, _, pre_calls, _ = _eval_fixed_point(
            v_init, idx0, jnp.array(jnp.inf, v_init.dtype), 200)
    else:
        idx0 = jnp.zeros(coh.shape, jnp.int32)
        v_start = v_init
        pre_calls = jnp.int32(0)

    def cond(carry):
        _, _, _, dist, it, same, tol_eff = carry
        return (dist >= tol_eff) & (it < max_iter) & jnp.logical_not(same)

    def body(carry):
        v, idx_prev, idx_prev2, _, it, _, _ = carry
        idx = improve(v, idx_prev)
        v_new = evaluate(v, idx)
        diff = jnp.abs(v_new - v)
        dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
        # Policy-stability termination (the Howard policy-iteration stop):
        # improvement returning the policy unchanged — or returning the
        # policy of two rounds ago, an exact period-2 cycle — means further
        # rounds only trade f32 flatness wobble: near the grid top the
        # choice objective is flat below value resolution, so the discrete
        # argmax can oscillate between equal-value cells forever while the
        # value sup-norm criterion wanders in the rounding band (cf. the
        # EGM noise_floor_ulp rationale). Both tests are DISCRETE and
        # drift-proof: a genuinely converging policy moves monotonically
        # and never revisits an earlier ITERATE, so neither fires early
        # (pinned by TestContinuousVFI value-dominance in f64). The repeat
        # test arms after round one and the cycle test after round two —
        # before those, idx_prev/idx_prev2 still hold the all-zeros INIT
        # sentinel, a corner policy a transient iterate could legitimately
        # equal without it being a revisit. A proximity gate (dist within
        # 1e3x tol) guards the one theoretical hole: modified policy
        # iteration with finite evaluation sweeps is not monotone, so a
        # policy 2-cycle far from the fixed point would otherwise
        # terminate — and the post-exit polish only re-evaluates, never
        # re-improves, so the suboptimal member would be returned without
        # any convergence signal (ADVICE round 2).
        near = dist < 1e3 * tol
        # Warm starts arm one round earlier: idx_prev at round one is the
        # warm policy (a real, value-consistent iterate), not the all-zeros
        # init sentinel the cold arming delay exists for.
        rep = jnp.all(idx == idx_prev) & (jnp.bool_(True) if warm else (it > 0))
        cyc = jnp.all(idx == idx_prev2) & ((it > 0) if warm else (it > 1))
        same = near & (rep | cyc)
        return v_new, idx, idx_prev, dist, it + 1, same, _tol_eff_of(v_new)

    init = (v_start, idx0, idx0,
            jnp.array(jnp.inf, v_init.dtype), jnp.int32(0), jnp.array(False),
            tol_c)
    v, idx, _, dist, it, same, tol_eff = jax.lax.while_loop(cond, body, init)

    # Policy-repeat exits still owe v a polish: with the policy fixed, each
    # evaluate() burst contracts the value residual by ~beta^howard_steps,
    # so iterating pure evaluation to the SAME value criterion delivers the
    # tolerance the value-based stop would have — without re-running the
    # gather-heavy improvement rounds (the whole point of the early exit).
    v, dist, pol_calls, tol_eff = jax.lax.cond(
        same,
        lambda c: _eval_fixed_point(c[0], idx, c[1], 50),
        lambda c: (c[0], c[1], jnp.int32(0), c[2]),
        (v, dist, tol_eff),
    )
    eval_sweeps = max(howard_steps, 1) * (pre_calls + it + pol_calls)

    policy_k = a_grid[idx]
    if golden_iters > 0:
        # One continuous refinement within the bracketing cells of the
        # converged discrete policy: the interval is at most two cells wide,
        # so f32 flatness jitter is bounded by the grid resolution the
        # discrete solution already has — it can only improve the policy.
        #
        # The search interval spans at most the 3 cells [idx-1, idx+2], so
        # the 4 bracketing EV and grid values are pre-gathered ONCE and every
        # golden iteration works in that local frame (a 3-wide one-hot
        # select) — the earlier per-iteration global locate + element
        # gathers cost ~10 [N, na] gathers x 48 iterations = 9.6 s of the
        # 11.2 s warm 400k solve (measured round 5); this form is ~60 ms.
        EV = expectation(P, v, beta)
        jbase = jnp.clip(idx - 1, 0, na - 4)
        j4 = jbase[:, :, None] + jnp.arange(4, dtype=jnp.int32)   # [N,na,4]
        E4 = jnp.take_along_axis(EV, j4.reshape(N, -1), axis=1
                                 ).reshape(N, na, 4)
        if grid_power > 0.0:
            a4 = a_grid[0] + (a_grid[-1] - a_grid[0]) * (
                j4.astype(v.dtype) / (na - 1)) ** grid_power
        else:
            a4 = a_grid[j4]

        def sel(X, o):
            # One-hot select along the (3- or 4-wide) local trailing axis.
            return jnp.sum(jnp.where(
                jnp.arange(X.shape[-1], dtype=jnp.int32) == o[..., None],
                X, 0.0), axis=-1)

        def f_cont(ap):
            # Cell within the local 4-point frame containing ap (0..2).
            o = jnp.sum(a4[..., 1:3] <= ap[..., None], axis=-1
                        ).astype(jnp.int32)
            a0, a1 = sel(a4[..., :3], o), sel(a4[..., 1:], o)
            e0, e1 = sel(E4[..., :3], o), sel(E4[..., 1:], o)
            t = (ap - a0) / (a1 - a0)
            c = jnp.maximum(coh - ap, c_floor)
            return _u(c, sigma) + e0 * (1.0 - t) + e1 * t

        lo_r = jnp.maximum(
            sel(a4, jnp.clip(idx - 1, 0, na - 1) - jbase), amin)
        hi_r = jnp.maximum(
            jnp.minimum(sel(a4, jnp.minimum(idx + 1, na - 1) - jbase), coh),
            lo_r)
        policy_k = golden_section_max(f_cont, lo_r, hi_r, n_iters=golden_iters)

    policy_c = jnp.maximum(coh - policy_k, c_floor)
    return VFISolution(v, idx, policy_k, policy_c,
                       jnp.ones_like(policy_k), it, dist, tol_eff,
                       eval_sweeps.astype(jnp.int32))


@partial(jax.jit, static_argnames=("lo", "hi", "power", "n"))
def _warm_stage_idx(warm_policy_k, g, *, lo: float, hi: float, power: float,
                    n: int):
    """Re-sample a final-grid savings policy onto an n-point stage grid and
    snap to the nearest stage-grid index — ONE dispatch (the eager op chain
    costs ~15 sequential ~100 ms round trips per stage on this image's
    remote TPU transport; measured as the bulk of an 11.5 s warm 400k
    solve before this was fused)."""
    if power <= 0.0:
        # Both the prolongation and the closed-form locator divide by the
        # spacing exponent; 0.0 (the continuous solver's "not power-spaced"
        # sentinel) would otherwise surface as a bare ZeroDivisionError at
        # trace time, far from the caller that passed warm_policy_k.
        raise ValueError(
            "a warm-start policy (warm_policy_k) can only be re-sampled onto "
            "stage grids of a power-spaced final grid: grid_power must be > 0, "
            f"got {power}"
        )
    from aiyagari_tpu.ops.interp import power_bucket_index, prolong_power_grid

    pk = (warm_policy_k if n == warm_policy_k.shape[-1] else
          prolong_power_grid(warm_policy_k, lo, hi, power, n))
    j = power_bucket_index(g, pk, lo, hi, power)
    return jnp.where(jnp.abs(g[j + 1] - pk) < jnp.abs(g[j] - pk),
                     j + 1, j).astype(jnp.int32)


def solve_aiyagari_vfi_multiscale(a_grid, s, P, r, w, amin, *, sigma: float,
                                  beta: float, tol: float, max_iter: int,
                                  grid_power: float,
                                  howard_steps: int = 20, golden_iters: int = 48,
                                  coarsest: int = 400,
                                  refine_factor: int = 10,
                                  relative_tol: bool = False,
                                  noise_floor_ulp: float = 0.0,
                                  warm_policy_k=None) -> VFISolution:
    """Grid-sequenced continuous VFI: solve coarse, prolong the VALUE function
    to each finer power grid (ops/interp.prolong_power_grid — closed-form
    bucket, one dispatch per stage), and re-converge there.

    Same nested-iteration rationale as solve_aiyagari_egm_multiscale: a cold
    fine-grid start pays ~log(d0/tol)/log(1/beta) improvement rounds, each a
    coarse-to-fine index search whose take_along_axis gathers are the TPU
    bottleneck; warm-starting from the coarse value cuts d0 to the coarse
    grid's discretization error, so the expensive fine stages run a handful
    of rounds. Identical fixed point to the single-grid
    solve_aiyagari_vfi_continuous (same operator and tolerance on the final
    grid; pinned by test_solvers.TestMultiscaleVFI).

    grid_power is REQUIRED (no default) and must be a_grid's actual spacing
    exponent: both the stage-grid construction and the closed-form locators
    trust it, and a mismatch converges to a silently wrong policy rather
    than erroring.

    warm_policy_k (optional [N, na_final] on the FINAL grid, e.g. a
    converged EGM solution's policy_k) is the cross-method policy warm
    start: each stage re-samples it onto the stage grid, snaps to the
    nearest stage-grid index, and passes it as
    solve_aiyagari_vfi_continuous's idx_init — so every stage (including
    the expensive final one) starts at a near-optimal policy and spends
    its wall on policy EVALUATION, not improvement-round walking. See
    solve_aiyagari_vfi_egm_warmstart for the composed recipe.
    """
    from aiyagari_tpu.ops.interp import prolong_power_grid
    from aiyagari_tpu.utils.grids import stage_grid, stage_sizes

    if grid_power <= 0.0:
        # 0.0 is solve_aiyagari_vfi_continuous's "not power-spaced" sentinel;
        # here it would collapse every stage grid to a point (t**0 == 1) and
        # poison the prolongation with 0/0 — fail loudly instead.
        raise ValueError(
            "solve_aiyagari_vfi_multiscale requires a power-spaced grid: pass "
            f"its actual spacing exponent as grid_power, got {grid_power}"
        )
    n_final = int(a_grid.shape[-1])
    dtype = a_grid.dtype
    # One batched fetch through the id-keyed cache instead of two eager
    # per-element float() round trips (~100 ms each on the remote TPU
    # transport — solvers/egm._cached_grid_bounds rationale; found by the
    # AIYA202 lint).
    from aiyagari_tpu.solvers.egm import _cached_grid_bounds

    lo, hi = _cached_grid_bounds(a_grid)
    sizes = stage_sizes(n_final, coarsest, refine_factor)

    sol = None
    for i, n in enumerate(sizes):
        g = a_grid if n == n_final else stage_grid(n, lo, hi, grid_power, dtype)
        v = (jnp.zeros((s.shape[0], n), dtype) if i == 0
             else prolong_power_grid(sol.v, lo, hi, grid_power, n))
        idx_i = None
        if warm_policy_k is not None:
            idx_i = _warm_stage_idx(warm_policy_k, g, lo=lo, hi=hi,
                                    power=grid_power, n=n)
        sol = solve_aiyagari_vfi_continuous(
            v, g, s, P, r, w, amin, sigma=sigma, beta=beta, tol=tol,
            max_iter=max_iter, howard_steps=howard_steps,
            # In-cell continuous refinement only matters on the final grid.
            golden_iters=golden_iters if n == n_final else 0,
            relative_tol=relative_tol, grid_power=grid_power,
            noise_floor_ulp=noise_floor_ulp, idx_init=idx_i,
        )
    return sol


def solve_aiyagari_vfi_egm_warmstart(a_grid, s, P, r, w, amin, *, sigma: float,
                                     beta: float, tol: float, max_iter: int,
                                     grid_power: float,
                                     howard_steps: int = 15,
                                     golden_iters: int = 48,
                                     coarsest: int = 400,
                                     refine_factor: int = 32,
                                     relative_tol: bool = False,
                                     noise_floor_ulp: float = 0.0,
                                     egm_solution=None) -> VFISolution:
    """Cross-method warm start for the north-star-scale VFI: obtain the
    converged EGM consumption policy (O(na) per sweep — ~0.2 s at 400k,
    BENCH_r04), map its savings policy to grid indices, and run the
    multiscale slab VFI from it. The improvement rounds then only VERIFY
    the policy (1-3 rounds) instead of walking to it (17-31 rounds cold at
    40k-400k), and the wall is dominated by policy evaluation — the
    structurally cheap part of the slab solver.

    The fixed point is the same as the cold solve's (same operator, same
    stopping rule; pinned by test_solvers.TestWarmStartVFI). egm_solution
    lets a caller that already holds a converged EGMSolution (the bench
    times the EGM leg separately) skip the inner solve.

    Defaults are the measured-best warm recipe at 400k on the v5e
    (round-5 A/B, BENCHMARKS.md): a 3-stage ladder (refine_factor=32 —
    the 4-stage default pays ~0.1 s of stage overhead for warmth the EGM
    policy already provides) and howard_steps=15 (per-call contraction
    beta^15 ~ 0.54 keeps the value stop honest while halving the
    evaluation sweeps of hs=25; hs <= 8 shaves ~80 ms more but the
    per-call contraction degrades to ~0.7-0.8, loosening what the
    stopping band certifies).
    """
    if egm_solution is None:
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

        egm_solution = solve_aiyagari_egm_multiscale(
            a_grid, s, P, r, w, amin, sigma=sigma, beta=beta, tol=tol,
            max_iter=max_iter, grid_power=grid_power,
            noise_floor_ulp=noise_floor_ulp)
    return solve_aiyagari_vfi_multiscale(
        a_grid, s, P, r, w, amin, sigma=sigma, beta=beta, tol=tol,
        max_iter=max_iter, grid_power=grid_power, howard_steps=howard_steps,
        golden_iters=golden_iters, coarsest=coarsest,
        refine_factor=refine_factor, relative_tol=relative_tol,
        noise_floor_ulp=noise_floor_ulp,
        warm_policy_k=egm_solution.policy_k)


@partial(jax.jit, static_argnames=("tol", "max_iter", "howard_steps", "relative_tol", "progress_every", "noise_floor_ulp", "ladder", "telemetry", "sentinel", "faults"))
def solve_aiyagari_vfi_labor(v_init, a_grid, labor_grid, s, P, r, w, *, sigma,
                             beta, psi, eta, tol: float,
                             max_iter: int, howard_steps: int = 0,
                             relative_tol: bool = False,
                             progress_every: int = 0,
                             noise_floor_ulp: float = 0.0,
                             ladder=None, telemetry=None, sentinel=None,
                             faults=None) -> VFISolution:
    """VFI with the joint (labor x a') discrete choice
    (Aiyagari_Endogenous_Labor_VFI.m:64-122). Preference scalars are traced
    operands (vmap/scenario-batch compatible), like solve_aiyagari_vfi —
    whose ladder/noise_floor_ulp semantics apply here verbatim (the hot
    stage's half-width [nl, N, na, na'] U4 tensor is the dominant HBM
    saving)."""
    stages = plan_stages(ladder, v_init.dtype, noise_floor_ulp)
    N, na = v_init.shape
    nl = labor_grid.shape[0]

    def run_stage(spec, v0, a_idx0, l_idx0, it0, tele_in, sent_in):
        dt = jnp.dtype(spec.dtype)
        prec = (matmul_precision_of(spec.matmul_precision)
                or jax.lax.Precision.DEFAULT)
        ag, lg = a_grid.astype(dt), labor_grid.astype(dt)
        sd, Pd = s.astype(dt), P.astype(dt)
        rd, wd = jnp.asarray(r).astype(dt), jnp.asarray(w).astype(dt)
        sig, bet, psid, etad = (jnp.asarray(x).astype(dt)
                                for x in (sigma, beta, psi, eta))
        tol_c = jnp.asarray(tol, dt)
        # Per-stage sentinel reference restart (dense-family rationale).
        sent_in = sentinel_stage_reset(sent_in)

        def eval_sweeps(v, a_idx, l_idx):
            if howard_steps <= 0:
                return v

            def body(v, _):
                return howard_eval_step_labor(
                    v, a_idx, l_idx, ag, lg, sd, Pd, rd, wd,
                    sigma=sig, beta=bet, psi=psid, eta=etad,
                    precision=prec,
                ), None

            v, _ = jax.lax.scan(body, v, None, length=howard_steps)
            return v

        def cond(carry):
            return sentinel_cond(
                carry[7], (carry[3] >= carry[5]) & (carry[4] < max_iter))

        # (tele rides at carry[6], the sentinel at carry[7]; indices 3/4/5
        # above are unchanged)

        # Hoist the loop-invariant [nl, N, na, na'] joint-choice utility when
        # it fits comfortably in HBM (reference scale: 10x7x400x400 f64 =
        # 90 MB); beyond that fall back to the scanned per-labor form. Peak
        # per-sweep memory is ~3x U4 (q = U4 + EV, plus the transpose copy
        # for the flat argmax), so the cap budgets U4 itself at 128 MB —
        # per stage dtype, so a hot f32 stage fits twice the grid.
        U4 = None
        if nl * N * na * na * jnp.dtype(dt).itemsize <= 128 * 1024 ** 2:
            U4 = labor_choice_utility_tensor(ag, lg, sd, rd, wd,
                                             sigma=sig, psi=psid, eta=etad,
                                             dtype=dt)

        def body(carry):
            v, a_idx, l_idx, _, it, _, tele, sent = carry
            if U4 is not None:
                v_new, a_idx, l_idx = bellman_step_labor_precomputed(
                    v, U4, Pd, beta=bet, precision=prec)
            else:
                v_new, a_idx, l_idx = bellman_step_labor(
                    v, ag, lg, sd, Pd, rd, wd, sigma=sig, beta=bet,
                    psi=psid, eta=etad, precision=prec
                )
            v_new = poison_iterate(faults, v_new, it)
            diff = jnp.abs(v_new - v)
            dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
            tol_eff = effective_tolerance(
                tol_c, jnp.max(jnp.abs(v_new)),
                noise_floor_ulp=spec.noise_floor_ulp,
                relative_tol=relative_tol, dtype=dt)
            device_progress("aiyagari_vfi_labor", it + 1, dist, every=progress_every)
            tele = telemetry_record(tele, dist)
            sent = sentinel_update(sent, dist, config=sentinel)
            v_new = eval_sweeps(v_new, a_idx, l_idx)
            return v_new, a_idx, l_idx, dist, it + 1, tol_eff, tele, sent

        init = (v0.astype(dt), a_idx0, l_idx0, jnp.array(jnp.inf, dt), it0,
                tol_c, tele_in, sent_in)
        return jax.lax.while_loop(cond, body, init)

    zeros_i = jnp.zeros(v_init.shape, jnp.int32)
    v, a_idx, l_idx = v_init, zeros_i, zeros_i
    it = jnp.int32(0)
    hot_it = jnp.int32(0)
    switch_dist = jnp.array(0.0, jnp.dtype(stages[-1].dtype))
    tele = telemetry_init(telemetry)
    sent = sentinel_init(sentinel)
    dist = tol_eff = None
    for spec in stages:
        v, a_idx, l_idx, dist, it, tol_eff, tele, sent = run_stage(
            spec, v, a_idx, l_idx, it, tele, sent)
        if not spec.is_final:
            hot_it = it
            switch_dist = dist.astype(switch_dist.dtype)
    dt_f = jnp.dtype(stages[-1].dtype)
    ag_f, lg_f = a_grid.astype(dt_f), labor_grid.astype(dt_f)
    policy_k = ag_f[a_idx]
    policy_l = lg_f[l_idx]
    policy_c = ((1.0 + jnp.asarray(r).astype(dt_f)) * ag_f[None, :]
                + jnp.asarray(w).astype(dt_f) * s.astype(dt_f)[:, None]
                * policy_l - policy_k)
    return VFISolution(v, a_idx, policy_k, policy_c, policy_l, it, dist,
                       tol_eff, hot_iterations=hot_it,
                       switch_distance=switch_dist, telemetry=tele,
                       sentinel=sent)
