"""Value-function-iteration solver: device-resident fixed point via
lax.while_loop, with optional Howard (policy-evaluation) acceleration.

The reference re-runs an interpreted double loop per sweep
(Aiyagari_VFI.m:65-90); here each sweep is one fused XLA program and the whole
fixed point stays on device — the host sees only the converged result.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.progress import device_progress
from aiyagari_tpu.ops.bellman import (
    bellman_step,
    bellman_step_labor,
    bellman_step_labor_precomputed,
    bellman_step_precomputed,
    choice_utility_tensor,
    labor_choice_utility_tensor,
    howard_eval_step,
    howard_eval_step_labor,
)

__all__ = [
    "VFISolution",
    "solve_aiyagari_vfi",
    "solve_aiyagari_vfi_labor",
    "solve_aiyagari_vfi_continuous",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class VFISolution:
    """Converged household solution on the grid. policy_l is all-ones for
    exogenous-labor models."""

    v: jax.Array              # [N, na]
    policy_idx: jax.Array     # [N, na] int32 argmax index into a_grid
    policy_k: jax.Array       # [N, na]
    policy_c: jax.Array       # [N, na]
    policy_l: jax.Array       # [N, na]
    iterations: jax.Array     # scalar int32
    distance: jax.Array       # scalar, final sup-norm


@partial(jax.jit, static_argnames=("sigma", "beta", "tol", "max_iter", "howard_steps", "block_size", "relative_tol", "use_pallas", "progress_every"))
def solve_aiyagari_vfi(v_init, a_grid, s, P, r, w, *, sigma: float, beta: float,
                       tol: float, max_iter: int, howard_steps: int = 0,
                       block_size: int = 0, relative_tol: bool = False,
                       use_pallas: bool = False, progress_every: int = 0) -> VFISolution:
    """Iterate the Bellman operator to a sup-norm fixed point.

    Convergence: max|v_new - v| < tol, matching Aiyagari_VFI.m:85 (absolute
    sup-norm, tol 1e-5, <=1000 sweeps). howard_steps>0 inserts that many
    policy-evaluation sweeps after each improvement (not used by the reference
    for Aiyagari, exposed for the scaled-up runs). progress_every>0 emits an
    in-jit telemetry record every that-many sweeps (diagnostics.progress;
    0 = off, zero cost).
    """

    def eval_sweeps(v, idx):
        if howard_steps <= 0:
            return v

        def body(v, _):
            return howard_eval_step(v, idx, a_grid, s, P, r, w, sigma=sigma, beta=beta), None

        v, _ = jax.lax.scan(body, v, None, length=howard_steps)
        return v

    def cond(carry):
        _, _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    # Dense path: the masked choice-utility tensor is loop-invariant, so
    # compute it once here and keep only EV + add + max inside the while_loop
    # (choice_utility_tensor docstring). Blocked/Pallas paths keep the fused
    # per-sweep form — at their scales the [N, na, na'] tensor is the thing
    # that must NOT be materialized.
    na = v_init.shape[1]
    dense = block_size <= 0 or block_size >= na
    U = (choice_utility_tensor(a_grid, s, r, w, sigma=sigma, dtype=v_init.dtype)
         if dense and not use_pallas else None)

    def body(carry):
        v, idx, _, it = carry
        if U is not None:
            v_new, idx = bellman_step_precomputed(v, U, P, beta=beta)
        else:
            v_new, idx = bellman_step(v, a_grid, s, P, r, w, sigma=sigma, beta=beta,
                                      block_size=block_size, use_pallas=use_pallas)
        diff = jnp.abs(v_new - v)
        dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
        device_progress("aiyagari_vfi", it + 1, dist, every=progress_every)
        v_new = eval_sweeps(v_new, idx)
        return v_new, idx, dist, it + 1

    init = (
        v_init,
        jnp.zeros(v_init.shape, jnp.int32),
        jnp.array(jnp.inf, v_init.dtype),
        jnp.int32(0),
    )
    v, idx, dist, it = jax.lax.while_loop(cond, body, init)
    policy_k = a_grid[idx]
    policy_c = (1.0 + r) * a_grid[None, :] + w * s[:, None] - policy_k
    return VFISolution(v, idx, policy_k, policy_c, jnp.ones_like(policy_k), it, dist)


@partial(jax.jit, static_argnames=("sigma", "beta", "tol", "max_iter", "howard_steps",
                                   "golden_iters", "relative_tol", "grid_power"))
def solve_aiyagari_vfi_continuous(v_init, a_grid, s, P, r, w, amin, *, sigma: float,
                                  beta: float, tol: float, max_iter: int,
                                  howard_steps: int = 20, golden_iters: int = 48,
                                  relative_tol: bool = False,
                                  grid_power: float = 0.0) -> VFISolution:
    """Continuous-choice VFI: golden-section maximization of
    u(coh - a') + interp(EV, a') over a' in [amin, coh), vmapped over all
    (state, asset) points — O(na) per sweep instead of the discrete search's
    O(na^2), so it scales to grids 1000x the reference's 400 points.

    This is the same solver family as the Krusell-Smith Howard VFI
    (solvers/ks_vfi.py, replacing Krusell_Smith_VFI.m:141-204's fminbnd);
    here applied to the Aiyagari block. EV is interpolated linearly in a'
    (concavity-safe); Howard evaluation sweeps amortize each improvement.
    Returns a VFISolution whose policy_idx is the nearest-grid snap of the
    continuous policy.
    """
    from aiyagari_tpu.ops.golden import golden_section_max
    from aiyagari_tpu.ops.interp import bucket_index, power_bucket_index
    from aiyagari_tpu.utils.utility import crra_utility as _u

    N, na = v_init.shape
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]          # [N, na]
    # Choice set [amin, min(coh, amax)]: capped at the top knot so the search
    # never optimizes against linearly-extrapolated continuation values (the
    # discrete solver truncates at the grid top the same way).
    hi_choice = jnp.clip(coh - 1e-10, amin, a_grid[-1])

    def locate(q):
        # grid_power > 0 means a_grid is power-spaced: O(1) closed-form
        # locator instead of a search (ops/interp.power_bucket_index).
        if grid_power > 0.0:
            return power_bucket_index(a_grid, q, a_grid[0], a_grid[-1], grid_power)
        return bucket_index(a_grid, q)

    def interp_weights(ap):
        idx = locate(ap)                                         # [N, na]
        x0 = a_grid[idx]
        t = (ap - x0) / (a_grid[idx + 1] - x0)
        return idx, t

    def ev_at(EV, idx, t):
        e0 = jnp.take_along_axis(EV, idx, axis=1)
        e1 = jnp.take_along_axis(EV, idx + 1, axis=1)
        return e0 * (1.0 - t) + e1 * t

    # Dtype-aware consumption floor: a literal like 1e-300 underflows to 0.0
    # in f32 and would turn the infeasibility penalty into u(0) = -inf.
    c_floor = jnp.finfo(v_init.dtype).tiny

    def value_given_ev(EV, ap):
        idx, t = interp_weights(ap)
        c = jnp.maximum(coh - ap, c_floor)
        return _u(c, sigma) + ev_at(EV, idx, t)

    def improve(v):
        EV = beta * P @ v   # hoisted: one expectation matmul per improvement
        f = lambda ap: value_given_ev(EV, ap)
        lo = jnp.full_like(coh, amin)
        return golden_section_max(f, lo, hi_choice, n_iters=golden_iters)

    def howard(v, pol):
        # The policy is fixed across sweeps: locate it once, re-gather EV only.
        idx, t = interp_weights(pol)
        u_pol = _u(jnp.maximum(coh - pol, c_floor), sigma)

        def sweep(v, _):
            EV = beta * P @ v
            return u_pol + ev_at(EV, idx, t), None

        v, _ = jax.lax.scan(sweep, v, None, length=max(howard_steps, 1))
        return v

    def cond(carry):
        _, _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        v, _, _, it = carry
        pol = improve(v)
        v_new = howard(v, pol)
        diff = jnp.abs(v_new - v)
        dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
        return v_new, pol, dist, it + 1

    init = (v_init, jnp.zeros_like(coh), jnp.array(jnp.inf, v_init.dtype), jnp.int32(0))
    v, policy_k, dist, it = jax.lax.while_loop(cond, body, init)
    policy_c = coh - policy_k
    idx = bucket_index(a_grid, policy_k, hi_clip=na - 1)
    return VFISolution(v, idx.astype(jnp.int32), policy_k, policy_c,
                       jnp.ones_like(policy_k), it, dist)


@partial(jax.jit, static_argnames=("sigma", "beta", "psi", "eta", "tol", "max_iter", "howard_steps", "relative_tol", "progress_every"))
def solve_aiyagari_vfi_labor(v_init, a_grid, labor_grid, s, P, r, w, *, sigma: float,
                             beta: float, psi: float, eta: float, tol: float,
                             max_iter: int, howard_steps: int = 0,
                             relative_tol: bool = False,
                             progress_every: int = 0) -> VFISolution:
    """VFI with the joint (labor x a') discrete choice
    (Aiyagari_Endogenous_Labor_VFI.m:64-122)."""

    def eval_sweeps(v, a_idx, l_idx):
        if howard_steps <= 0:
            return v

        def body(v, _):
            return howard_eval_step_labor(
                v, a_idx, l_idx, a_grid, labor_grid, s, P, r, w,
                sigma=sigma, beta=beta, psi=psi, eta=eta,
            ), None

        v, _ = jax.lax.scan(body, v, None, length=howard_steps)
        return v

    def cond(carry):
        return (carry[3] >= tol) & (carry[4] < max_iter)

    # Hoist the loop-invariant [nl, N, na, na'] joint-choice utility when it
    # fits comfortably in HBM (reference scale: 10x7x400x400 f64 = 90 MB);
    # beyond that fall back to the scanned per-labor form. Peak per-sweep
    # memory is ~3x U4 (q = U4 + EV, plus the transpose copy for the flat
    # argmax), so the cap budgets U4 itself at 128 MB.
    N, na = v_init.shape
    nl = labor_grid.shape[0]
    U4 = None
    if nl * N * na * na * jnp.dtype(v_init.dtype).itemsize <= 128 * 1024 ** 2:
        U4 = labor_choice_utility_tensor(a_grid, labor_grid, s, r, w,
                                         sigma=sigma, psi=psi, eta=eta,
                                         dtype=v_init.dtype)

    def body(carry):
        v, a_idx, l_idx, _, it = carry
        if U4 is not None:
            v_new, a_idx, l_idx = bellman_step_labor_precomputed(v, U4, P, beta=beta)
        else:
            v_new, a_idx, l_idx = bellman_step_labor(
                v, a_grid, labor_grid, s, P, r, w, sigma=sigma, beta=beta, psi=psi, eta=eta
            )
        diff = jnp.abs(v_new - v)
        dist = jnp.max(diff / (jnp.abs(v) + 1e-10)) if relative_tol else jnp.max(diff)
        device_progress("aiyagari_vfi_labor", it + 1, dist, every=progress_every)
        v_new = eval_sweeps(v_new, a_idx, l_idx)
        return v_new, a_idx, l_idx, dist, it + 1

    zeros_i = jnp.zeros(v_init.shape, jnp.int32)
    init = (v_init, zeros_i, zeros_i, jnp.array(jnp.inf, v_init.dtype), jnp.int32(0))
    v, a_idx, l_idx, dist, it = jax.lax.while_loop(cond, body, init)
    policy_k = a_grid[a_idx]
    policy_l = labor_grid[l_idx]
    policy_c = (1.0 + r) * a_grid[None, :] + w * s[:, None] * policy_l - policy_k
    return VFISolution(v, a_idx, policy_k, policy_c, policy_l, it, dist)
