"""Endogenous-grid-method solver: fixed point on the consumption policy via
lax.while_loop (Carroll 2006). Reference: Aiyagari_EGM.m:74-110 and
Aiyagari_Endogenous_Labor_EGM.m:67-107.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.progress import device_progress
from aiyagari_tpu.ops.egm import egm_step, egm_step_labor

__all__ = ["EGMSolution", "solve_aiyagari_egm", "solve_aiyagari_egm_labor"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EGMSolution:
    """Converged policies on the exogenous grid. policy_l is all-ones for
    exogenous-labor models."""

    policy_c: jax.Array       # [N, na]
    policy_k: jax.Array       # [N, na]
    policy_l: jax.Array       # [N, na]
    iterations: jax.Array
    distance: jax.Array


@partial(jax.jit, static_argnames=("sigma", "beta", "tol", "max_iter", "relative_tol", "progress_every"))
def solve_aiyagari_egm(C_init, a_grid, s, P, r, w, amin, *, sigma: float, beta: float,
                       tol: float, max_iter: int, relative_tol: bool = False,
                       progress_every: int = 0) -> EGMSolution:
    """Iterate the EGM operator until max|C_new - C| < tol
    (Aiyagari_EGM.m:106, tol 1e-5, <=1000 iterations). progress_every>0 emits
    an in-jit telemetry record every that-many sweeps (diagnostics.progress)."""

    def cond(carry):
        _, _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        C, _, _, it = carry
        C_new, policy_k = egm_step(C, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta)
        diff = jnp.abs(C_new - C)
        dist = jnp.max(diff / (jnp.abs(C) + 1e-10)) if relative_tol else jnp.max(diff)
        device_progress("aiyagari_egm", it + 1, dist, every=progress_every)
        return C_new, policy_k, dist, it + 1

    init = (C_init, jnp.zeros_like(C_init), jnp.array(jnp.inf, C_init.dtype), jnp.int32(0))
    C, policy_k, dist, it = jax.lax.while_loop(cond, body, init)
    return EGMSolution(C, policy_k, jnp.ones_like(C), it, dist)


@partial(jax.jit, static_argnames=("sigma", "beta", "psi", "eta", "tol", "max_iter", "relative_tol", "progress_every"))
def solve_aiyagari_egm_labor(C_init, a_grid, s, P, r, w, amin, *, sigma: float, beta: float,
                             psi: float, eta: float, tol: float, max_iter: int,
                             relative_tol: bool = False,
                             progress_every: int = 0) -> EGMSolution:
    """EGM with the closed-form intratemporal labor FOC
    (Aiyagari_Endogenous_Labor_EGM.m:67-107)."""

    def cond(carry):
        return (carry[3] >= tol) & (carry[4] < max_iter)

    def body(carry):
        C, _, _, _, it = carry
        C_new, policy_k, policy_l = egm_step_labor(
            C, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta, psi=psi, eta=eta
        )
        diff = jnp.abs(C_new - C)
        dist = jnp.max(diff / (jnp.abs(C) + 1e-10)) if relative_tol else jnp.max(diff)
        device_progress("aiyagari_egm_labor", it + 1, dist, every=progress_every)
        return C_new, policy_k, policy_l, dist, it + 1

    z = jnp.zeros_like(C_init)
    init = (C_init, z, z, jnp.array(jnp.inf, C_init.dtype), jnp.int32(0))
    C, policy_k, policy_l, dist, it = jax.lax.while_loop(cond, body, init)
    return EGMSolution(C, policy_k, policy_l, it, dist)
