"""Endogenous-grid-method solver: fixed point on the consumption policy via
lax.while_loop (Carroll 2006). Reference: Aiyagari_EGM.m:74-110 and
Aiyagari_Endogenous_Labor_EGM.m:67-107.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.faults import force_escape_point, poison_iterate
from aiyagari_tpu.diagnostics.progress import device_progress
from aiyagari_tpu.diagnostics.sentinel import (
    sentinel_cond,
    sentinel_init,
    sentinel_stage_reset,
    sentinel_update,
)
from aiyagari_tpu.diagnostics.telemetry import (
    telemetry_init,
    telemetry_record,
    telemetry_set_trips,
)
from aiyagari_tpu.ops.accel import accel_init, accel_step, project_floor
from aiyagari_tpu.ops.egm import constrained_consumption_labor, egm_step, egm_step_labor
from aiyagari_tpu.ops.implicit import fixed_point_vjp
from aiyagari_tpu.ops.interp import prolong_power_grid
from aiyagari_tpu.ops.precision import hot_only, plan_stages
from aiyagari_tpu.solvers._stopping import effective_tolerance

# Multigrid ladder defaults, shared with the mesh warm-start route
# (equilibrium/bisection.py) so the stage geometry cannot drift.
LADDER_COARSEST = 400
LADDER_REFINE = 10
# Grids at or below this size take the single-stage solve even when grid
# sequencing is on: the ladder's extra stages cost more than the ~290 cold
# sweeps they save at small n. Shared by every grid-sequencing gate
# (equilibrium/bisection.py routes) so the trigger cannot drift per route.
LADDER_MIN_FINE = 1600

__all__ = [
    "EGMSolution",
    "LADDER_COARSEST",
    "LADDER_MIN_FINE",
    "LADDER_REFINE",
    "initial_consumption_guess",
    "ladder_warm_start",
    "ladder_warm_start_labor",
    "solve_aiyagari_egm",
    "solve_aiyagari_egm_implicit",
    "solve_aiyagari_egm_safe",
    "solve_aiyagari_egm_labor",
    "solve_aiyagari_egm_labor_safe",
    "solve_aiyagari_egm_multiscale",
    "solve_aiyagari_egm_labor_multiscale",
]


@jax.jit
def initial_consumption_guess(a_grid, s, r, w):
    """EGM warm start: consume cash-on-hand at mean productivity
    (Aiyagari_EGM.m:64). The single source of truth for the reference's
    initial guess — used by the bisection loop, the multiscale stages, and
    the benchmark. Jitted: one host dispatch instead of an eager op chain
    (~100 ms per op round trip on this image's remote TPU transport)."""
    mean_s = jnp.mean(s)
    base = (1.0 + r) * a_grid + w * mean_s
    return jnp.broadcast_to(base[None, :], (s.shape[0], a_grid.shape[0]))


@jax.jit
def _grid_bounds(a):
    return a[0], a[-1]


_GRID_BOUNDS_CACHE: dict = {}


def _cached_grid_bounds(a_grid):
    """(lo, hi) of a grid array as host floats, fetched ONCE per array.

    Why this exists: on this image's remote TPU transport every host read
    is a ~100 ms round trip, and the multiscale entry points need lo/hi as
    STATIC values (stage grids and prolongation are compile-time
    parameterized). Eager `float(a_grid[0])` + `float(a_grid[-1])` cost two
    dispatches and two sequential fetches per call — measured ~45% of the
    entire 400k north-star solve. One jitted pair extraction + one batched
    `jax.device_get` costs a single round trip, and the id-keyed cache
    (holding the array alive, so ids cannot be reused) makes repeat solves
    on the same grid — the bench loop, every bisection iteration — free."""
    key = id(a_grid)
    hit = _GRID_BOUNDS_CACHE.get(key)
    if hit is not None and hit[0] is a_grid:
        return hit[1], hit[2]
    lo, hi = (float(v) for v in jax.device_get(_grid_bounds(a_grid)))
    if len(_GRID_BOUNDS_CACHE) >= 8:
        _GRID_BOUNDS_CACHE.pop(next(iter(_GRID_BOUNDS_CACHE)))
    _GRID_BOUNDS_CACHE[key] = (a_grid, lo, hi)
    return lo, hi


def _fetch_scalars(sol: "EGMSolution") -> "EGMSolution":
    """Replace the solution's scalar fields with host values in ONE batched
    transfer (jax.device_get pipelines the gets — measured ~1 round trip
    for 4 scalars vs 4 sequential ~100 ms float() fetches on the axon
    transport). The escape-retry decision and the callers' convergence
    checks (bool(escaped), float(distance)) then cost nothing."""
    esc, dist, it, tol_eff = jax.device_get(
        (sol.escaped, sol.distance, sol.iterations, sol.tol_effective))
    return dataclasses.replace(sol, escaped=esc, distance=dist,
                               iterations=it, tol_effective=tol_eff)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EGMSolution:
    """Converged policies on the exogenous grid. policy_l is all-ones for
    exogenous-labor models. `escaped` is True iff some sweep's windowed
    fast-path inversion escaped its static windows (the NaN-poisoning
    contract, ops/interp.inverse_interp_power_grid) — a NaN distance with
    escaped=False is genuine numerical divergence, and retry wrappers must
    not mask it by re-solving."""

    policy_c: jax.Array       # [N, na]
    policy_k: jax.Array       # [N, na]
    policy_l: jax.Array       # [N, na]
    iterations: jax.Array
    distance: jax.Array
    escaped: jax.Array = dataclasses.field(default_factory=lambda: jnp.array(False))
    # The tolerance the stopping rule actually applied: == tol unless the
    # ulp-noise floor was engaged (solve_aiyagari_egm noise_floor_ulp).
    # Convergence checks should compare distance against THIS, not tol.
    tol_effective: jax.Array = dataclasses.field(default_factory=lambda: jnp.array(0.0))
    # Mixed-precision ladder telemetry (ops/precision.py; 0 when no ladder
    # ran): sweeps executed in the hot (pre-polish) stages — `iterations`
    # keeps counting ALL sweeps, so polish sweeps = iterations -
    # hot_iterations — and the residual at which the dtype switch fired.
    hot_iterations: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0, jnp.int32))
    switch_distance: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0.0))
    # Device-resident flight record (diagnostics/telemetry.py): the ring of
    # per-sweep residuals + stage dtypes + safeguard-trip counts carried
    # through the while_loop when SolverConfig.telemetry is set; None (the
    # default, an empty pytree leaf) when the recorder was compiled out.
    telemetry: object = None
    # Failure-sentinel state (diagnostics/sentinel.py): the structured
    # verdict (nan/stall/explode/escape) the loop early-exited with, when
    # SolverConfig.sentinel is set; None when the sentinel was compiled out.
    sentinel: object = None


@partial(jax.jit, static_argnames=("tol", "max_iter", "relative_tol", "progress_every", "grid_power", "noise_floor_ulp", "egm_kernel", "accel", "ladder", "telemetry", "sentinel", "faults"))
def solve_aiyagari_egm(C_init, a_grid, s, P, r, w, amin, *, sigma, beta,
                       tol: float, max_iter: int, relative_tol: bool = False,
                       progress_every: int = 0, grid_power: float = 0.0,
                       noise_floor_ulp: float = 0.0,
                       egm_kernel: str = "xla", accel=None,
                       ladder=None, telemetry=None, sentinel=None,
                       faults=None) -> EGMSolution:
    """Iterate the EGM operator until max|C_new - C| < tol
    (Aiyagari_EGM.m:106, tol 1e-5, <=1000 iterations). progress_every>0 emits
    an in-jit telemetry record every that-many sweeps (diagnostics.progress).
    grid_power > 0 enables the gather-free power-grid inversion fast path
    (ops/egm.egm_step docstring). sigma/beta (and r, w, amin) are traced
    operands: one compile covers any preference values, and the whole solve
    vmaps over scenario batches (equilibrium/batched.py).

    noise_floor_ulp > 0 widens the absolute stopping tolerance to
    max(tol, noise_floor_ulp * eps(dtype) * max|C|) — the sweep operator's
    own rounding floor. Why: on fine grids in f32 the iterate reaches its
    fixed point in a handful of warm-started sweeps and then WANDERS in the
    ulp-noise band of the sup-norm (each sweep re-rounds 2.8M values at
    ~eps * |C|; tol 1e-5 is ~1.3 ulp at max|C| ~ 100), so the strict
    criterion burns ~30 extra full-size sweeps at 400k points waiting for
    the max over millions of points to randomly dip under tol
    (BENCHMARKS.md round-1 stage timings). A distance at the floor carries
    the same solution quality — the discretization error at those grids is
    orders of magnitude below it. No-op in f64 at any sane setting
    (eps ~ 2e-16) and at the reference's 400-point scale (the strict tol is
    reached before the band matters). The applied tolerance is returned as
    EGMSolution.tol_effective; convergence checks must use it.

    accel (an AccelConfig, static) opts into safeguarded Anderson/SQUAREM
    acceleration of the fixed point (ops/accel.py): the loop body still runs
    exactly one egm_step per iteration and stops on the same
    dist = max|F(C) - C| criterion, but the NEXT iterate is the accelerated
    proposal. The returned policies are always the SWEEP's output (the
    image, with its budget-consistent policy_k), never the extrapolated
    point — so the solution satisfies the stopping certificate identically
    to the plain route.

    ladder (a PrecisionLadderConfig, static) opts into the mixed-precision
    solve ladder (ops/precision.py): the early sweeps run in the ladder's
    hot dtype (f32 by default, matmul contraction at the stage's configured
    precision — bf16 MXU on TPU) inside their own while_loop until the
    residual reaches max(tol, switch_ulp * eps * max|C|), then the carry is
    cast up ONCE, the acceleration history restarts (stale hot-dtype
    residuals would poison the polish's normal equations), and the ordinary
    full-precision loop finishes to the reference criterion. `iterations`
    counts ALL sweeps; the hot-stage share and the residual at the switch
    are returned as EGMSolution.hot_iterations / .switch_distance. With
    relative_tol the criterion is already scale-free and the hot stage
    simply runs to tol.

    telemetry (a TelemetryConfig, static) carries a device-resident flight
    recorder through the loop (diagnostics/telemetry.py): the per-sweep
    residual and its stage dtype land in a fixed-length ring in the carry,
    accel safeguard trips are tallied, and the buffers come back as
    EGMSolution.telemetry. None compiles the recorder out entirely — the
    traced program is identical to the recorder-free one.

    egm_kernel (static, ops/egm.EGM_KERNELS — loudly validated) selects the
    sweep route per stage: "pallas_fused" runs every sweep as the one
    VMEM-resident Pallas kernel (ops/pallas_egm.py; generic-inversion
    semantics, never escapes, interpreted off-TPU), with the ladder's
    per-stage matmul precision threaded into its Euler contraction;
    "pallas_inverse" keeps the op chain but fuses the windowed grid
    inversion. The sentinel, telemetry, fault and acceleration carries
    compose with every route unchanged — they act on the sweep's OUTPUT.

    sentinel (a SentinelConfig, static) carries the failure sentinel
    (diagnostics/sentinel.py) through the loop: non-finite residuals (split
    into "escape" vs "nan" by the windowed-inversion escape flag), stalls,
    and explosions early-exit the loop with a structured verdict on
    EGMSolution.sentinel. faults (a FaultPlan, static) compiles in the
    deterministic injection points of diagnostics/faults.py — test/CI
    machinery, never production. Both None (the default) compile out
    entirely, same zero-cost contract as telemetry."""

    stages = plan_stages(ladder, C_init.dtype, noise_floor_ulp)
    proj = project_floor()

    def run_stage(spec, C0, pk0, it0, esc0, tele_in, sent_in):
        dt = jnp.dtype(spec.dtype)
        Cd = C0.astype(dt)
        ag, sd, Pd = a_grid.astype(dt), s.astype(dt), P.astype(dt)
        rd, wd, amind = (jnp.asarray(x).astype(dt) for x in (r, w, amin))
        sig, bet = jnp.asarray(sigma).astype(dt), jnp.asarray(beta).astype(dt)
        tol_c = jnp.asarray(tol, dt)
        ast0 = accel_init(Cd, accel) if accel is not None else None
        # Trip base for this stage: the accel history restarts per stage, so
        # the recorder's running total is stage base + the state's counter.
        trip0 = (tele_in.accel_trips
                 if (tele_in is not None and accel is not None) else None)
        # The sentinel's stall/explosion references restart per stage too —
        # a hot stage's noise-floor best must not stall the polish
        # (sentinel_stage_reset docstring; the accel-history lesson).
        sent_in = sentinel_stage_reset(sent_in)

        def cond(carry):
            _, _, _, dist, it, _, tol_eff, _, _, sent = carry
            return sentinel_cond(sent, (dist >= tol_eff) & (it < max_iter))

        def body(carry):
            C, _, _, _, it, esc, _, ast, tele, sent = carry
            C_new, policy_k, esc_new = egm_step(
                C, ag, sd, Pd, rd, wd, amind, sigma=sig, beta=bet,
                grid_power=grid_power, with_escape=True,
                egm_kernel=egm_kernel,
                matmul_precision=spec.matmul_precision)
            C_new = poison_iterate(faults, C_new, it)
            C_new, esc_new = force_escape_point(faults, C_new, esc_new)
            diff = jnp.abs(C_new - C)
            dist = jnp.max(diff / (jnp.abs(C) + 1e-10)) if relative_tol else jnp.max(diff)
            tol_eff = effective_tolerance(
                tol_c, jnp.max(jnp.abs(C_new)),
                noise_floor_ulp=spec.noise_floor_ulp,
                relative_tol=relative_tol, dtype=dt)
            device_progress("aiyagari_egm", it + 1, dist, every=progress_every)
            tele = telemetry_record(tele, dist)
            sent = sentinel_update(sent, dist, config=sentinel,
                                   escaped=esc | esc_new)
            if accel is None:
                C_next = C_new
            else:
                C_next, ast = accel_step(ast, C, C_new, accel=accel, project=proj)
                if trip0 is not None:
                    tele = telemetry_set_trips(tele, trip0 + ast.trips)
            return (C_next, C_new, policy_k, dist, it + 1, esc | esc_new,
                    tol_eff, ast, tele, sent)

        init = (Cd, Cd, pk0.astype(dt), jnp.array(jnp.inf, dt), it0, esc0,
                tol_c, ast0, tele_in, sent_in)
        out = jax.lax.while_loop(cond, body, init)
        # (image C, policy_k, dist, it, esc, tol_eff) — the image, not the
        # accelerated carry, crosses the stage boundary: it is the certified
        # sweep output the stopping rule measured.
        return out[1], out[2], out[3], out[4], out[5], out[6], out[8], out[9]

    C, policy_k = C_init, jnp.zeros_like(C_init)
    it, esc = jnp.int32(0), jnp.array(False)
    hot_it = jnp.int32(0)
    switch_dist = jnp.array(0.0, stages[-1].dtype)
    tele = telemetry_init(telemetry)
    sent = sentinel_init(sentinel)
    dist = tol_eff = None
    for spec in stages:
        C, policy_k, dist, it, esc, tol_eff, tele, sent = run_stage(
            spec, C, policy_k, it, esc, tele, sent)
        if not spec.is_final:
            hot_it = it
            switch_dist = dist.astype(switch_dist.dtype)
    return EGMSolution(C, policy_k, jnp.ones_like(C), it, dist, esc, tol_eff,
                       hot_it, switch_dist, telemetry=tele, sentinel=sent)


def solve_aiyagari_egm_safe(C_init, a_grid, s, P, r, w, amin, *, sigma: float,
                            beta: float, tol: float, max_iter: int,
                            relative_tol: bool = False, progress_every: int = 0,
                            grid_power: float = 0.0,
                            noise_floor_ulp: float = 0.0,
                            egm_kernel: str = "xla", accel=None,
                            ladder=None, telemetry=None, sentinel=None,
                            faults=None) -> EGMSolution:
    """solve_aiyagari_egm plus the host-level escape retry for the windowed
    fast-path inversion: if the power-grid inversion's query-block windows
    cannot cover the endogenous grid's local knot density, it poisons the
    sweep with NaN and raises the solution's `escaped` flag
    (ops/interp.inverse_interp_power_grid), the while_loop exits on the NaN
    distance, and this wrapper re-solves on the generic exact route
    (grid_power=0, egm_kernel="xla" — the most conservative sweep). Host-
    level by design — callers inside jit should use solve_aiyagari_egm
    directly and accept the documented poisoning contract. The retry arms
    on the `escaped` flag, not on NaN itself: genuine numerical divergence
    also yields a NaN distance (on any grid size), and re-solving there
    would double the cost only to return the same NaN. The fused Pallas
    route (egm_kernel="pallas_fused") never raises the flag — it scans the
    full knot row, so escapes cannot occur and the retry never arms — but
    the contract is preserved verbatim: injected escapes (FaultPlan
    .force_escape) and the windowed routes still retry exactly as before."""
    sol = solve_aiyagari_egm(C_init, a_grid, s, P, r, w, amin, sigma=sigma,
                             beta=beta, tol=tol, max_iter=max_iter,
                             relative_tol=relative_tol,
                             progress_every=progress_every,
                             grid_power=grid_power,
                             noise_floor_ulp=noise_floor_ulp,
                             egm_kernel=egm_kernel, accel=accel, ladder=ladder,
                             telemetry=telemetry, sentinel=sentinel,
                             faults=faults)
    if grid_power > 0.0 and bool(sol.escaped):
        sol = solve_aiyagari_egm(C_init, a_grid, s, P, r, w, amin, sigma=sigma,
                                 beta=beta, tol=tol, max_iter=max_iter,
                                 relative_tol=relative_tol,
                                 progress_every=progress_every,
                                 grid_power=0.0,
                                 noise_floor_ulp=noise_floor_ulp, accel=accel,
                                 ladder=ladder, telemetry=telemetry,
                                 sentinel=sentinel, faults=faults)
    return sol


def solve_aiyagari_egm_implicit(C_init, a_grid, s, P, r, w, amin, *, sigma,
                                beta, tol: float, max_iter: int,
                                grid_power: float = 0.0,
                                adjoint_tol: float = 1e-13,
                                adjoint_max_iter: int = 2000) -> EGMSolution:
    """Differentiable view of the converged EGM policy (ISSUE 17): solve the
    household problem exactly as solve_aiyagari_egm would — every input
    under lax.stop_gradient, so no gradient path attempts to enter the
    solver's while_loop — then wrap the converged (policy_c, policy_k) PAIR
    in ops/implicit.fixed_point_vjp with one differentiable egm_step as the
    fixed-point operator. Gradients w.r.t. (a_grid, s, P, r, w, amin, sigma,
    beta) flow through the IFT adjoint; the primal policies are
    bit-identical to the unwrapped solve (identity forward).

    The pair is wrapped jointly because policy_k is the budget-identity
    by-product of the same sweep: the step ignores its policy_k input, so
    the adjoint Jacobian is block-triangular and the Neumann solve
    converges at the contraction rate of the consumption update alone.

    Route pins: egm_kernel="xla" / matmul_precision="highest" — the Pallas
    routes carry no AD rules (same pin as transition/jacobian.py), and the
    adjoint should not inherit a relaxed hot-stage contraction. Telemetry,
    sentinel, accel and ladder knobs are deliberately absent here: they
    shape the PRIMAL iteration path, which the IFT adjoint never sees —
    callers needing them should run the plain solve for diagnostics and
    this wrapper for gradients.
    """
    sg = jax.lax.stop_gradient
    prim = solve_aiyagari_egm(
        sg(C_init), sg(a_grid), sg(s), sg(P), sg(r), sg(w), sg(amin),
        sigma=sg(sigma), beta=sg(beta), tol=tol, max_iter=max_iter,
        grid_power=grid_power, egm_kernel="xla")
    params = (a_grid, s, P, r, w, amin, sigma, beta)

    def step(x, p):
        C, _ = x
        ag, s_, P_, r_, w_, am_, sig_, bet_ = p
        return egm_step(C, ag, s_, P_, r_, w_, am_, sigma=sig_, beta=bet_,
                        grid_power=grid_power, with_escape=False,
                        egm_kernel="xla", matmul_precision="highest")

    C_d, k_d = fixed_point_vjp(step, (prim.policy_c, prim.policy_k), params,
                               tol=adjoint_tol, max_iter=adjoint_max_iter)
    return dataclasses.replace(prim, policy_c=C_d, policy_k=k_d)


@partial(jax.jit, static_argnames=("tol", "max_iter", "relative_tol", "progress_every", "grid_power", "noise_floor_ulp", "accel", "ladder", "telemetry", "sentinel", "faults"))
def solve_aiyagari_egm_labor(C_init, a_grid, s, P, r, w, amin, *, sigma, beta,
                             psi, eta, tol: float, max_iter: int,
                             relative_tol: bool = False,
                             progress_every: int = 0,
                             grid_power: float = 0.0,
                             noise_floor_ulp: float = 0.0,
                             accel=None, ladder=None,
                             telemetry=None, sentinel=None,
                             faults=None) -> EGMSolution:
    """EGM with the closed-form intratemporal labor FOC
    (Aiyagari_Endogenous_Labor_EGM.m:67-107). grid_power > 0 routes the
    consumption re-interpolation through the windowed value-interpolation
    fast path; noise_floor_ulp is the f32 stopping-rule floor; accel opts
    into safeguarded fixed-point acceleration of the consumption iterate;
    ladder opts into the mixed-precision solve ladder (hot-dtype sweeps,
    error-controlled switch, full-precision polish) — all exactly as in
    solve_aiyagari_egm (see its docstring). Only C is accelerated: the
    labor/asset policies are closed-form per sweep, so they stay consistent
    with the returned (sweep-output) C. The constrained-region static
    solution is rebuilt per ladder stage (it is loop-invariant but
    dtype-dependent)."""
    stages = plan_stages(ladder, C_init.dtype, noise_floor_ulp)
    proj = project_floor()

    def run_stage(spec, C0, pk0, pl0, it0, esc0, tele_in, sent_in):
        dt = jnp.dtype(spec.dtype)
        Cd = C0.astype(dt)
        ag, sd, Pd = a_grid.astype(dt), s.astype(dt), P.astype(dt)
        rd, wd, amind = (jnp.asarray(x).astype(dt) for x in (r, w, amin))
        sig, bet, psid, etad = (jnp.asarray(x).astype(dt)
                                for x in (sigma, beta, psi, eta))
        # Loop-invariant: the constrained-region static solution depends on
        # prices and the grid only, not the consumption iterate.
        c_con = constrained_consumption_labor(
            ag, sd, rd, wd, amind, sigma=sig, psi=psid, eta=etad
        )
        tol_c = jnp.asarray(tol, dt)
        ast0 = accel_init(Cd, accel) if accel is not None else None
        trip0 = (tele_in.accel_trips
                 if (tele_in is not None and accel is not None) else None)
        # Per-stage stall/explosion-reference restart (exogenous-family
        # rationale above).
        sent_in = sentinel_stage_reset(sent_in)

        def cond(carry):
            return sentinel_cond(
                carry[10], (carry[4] >= carry[7]) & (carry[5] < max_iter))

        def body(carry):
            C, _, _, _, _, it, esc, _, ast, tele, sent = carry
            C_new, policy_k, policy_l, esc_new = egm_step_labor(
                C, ag, sd, Pd, rd, wd, amind, sigma=sig, beta=bet,
                psi=psid, eta=etad, c_constrained=c_con,
                grid_power=grid_power, with_escape=True,
                matmul_precision=spec.matmul_precision,
            )
            C_new = poison_iterate(faults, C_new, it)
            C_new, esc_new = force_escape_point(faults, C_new, esc_new)
            diff = jnp.abs(C_new - C)
            dist = jnp.max(diff / (jnp.abs(C) + 1e-10)) if relative_tol else jnp.max(diff)
            tol_eff = effective_tolerance(
                tol_c, jnp.max(jnp.abs(C_new)),
                noise_floor_ulp=spec.noise_floor_ulp,
                relative_tol=relative_tol, dtype=dt)
            device_progress("aiyagari_egm_labor", it + 1, dist, every=progress_every)
            tele = telemetry_record(tele, dist)
            sent = sentinel_update(sent, dist, config=sentinel,
                                   escaped=esc | esc_new)
            if accel is None:
                C_next = C_new
            else:
                C_next, ast = accel_step(ast, C, C_new, accel=accel, project=proj)
                if trip0 is not None:
                    tele = telemetry_set_trips(tele, trip0 + ast.trips)
            return (C_next, C_new, policy_k, policy_l, dist, it + 1,
                    esc | esc_new, tol_eff, ast, tele, sent)

        init = (Cd, Cd, pk0.astype(dt), pl0.astype(dt),
                jnp.array(jnp.inf, dt), it0, esc0, tol_c, ast0, tele_in,
                sent_in)
        out = jax.lax.while_loop(cond, body, init)
        return (out[1], out[2], out[3], out[4], out[5], out[6], out[7],
                out[9], out[10])

    z = jnp.zeros_like(C_init)
    C, policy_k, policy_l = C_init, z, z
    it, esc = jnp.int32(0), jnp.array(False)
    hot_it = jnp.int32(0)
    switch_dist = jnp.array(0.0, stages[-1].dtype)
    tele = telemetry_init(telemetry)
    sent = sentinel_init(sentinel)
    dist = tol_eff = None
    for spec in stages:
        C, policy_k, policy_l, dist, it, esc, tol_eff, tele, sent = run_stage(
            spec, C, policy_k, policy_l, it, esc, tele, sent)
        if not spec.is_final:
            hot_it = it
            switch_dist = dist.astype(switch_dist.dtype)
    return EGMSolution(C, policy_k, policy_l, it, dist, esc, tol_eff,
                       hot_it, switch_dist, telemetry=tele, sentinel=sent)


def solve_aiyagari_egm_labor_safe(C_init, a_grid, s, P, r, w, amin, *,
                                  sigma: float, beta: float, psi: float,
                                  eta: float, tol: float, max_iter: int,
                                  relative_tol: bool = False,
                                  progress_every: int = 0,
                                  grid_power: float = 0.0,
                                  noise_floor_ulp: float = 0.0,
                                  accel=None, ladder=None,
                                  telemetry=None, sentinel=None,
                                  faults=None) -> EGMSolution:
    """Host-level escape retry for the labor family (the exact analogue of
    solve_aiyagari_egm_safe: re-solve on the generic route only when the
    windowed fast path actually escaped)."""
    sol = solve_aiyagari_egm_labor(C_init, a_grid, s, P, r, w, amin,
                                   sigma=sigma, beta=beta, psi=psi, eta=eta,
                                   tol=tol, max_iter=max_iter,
                                   relative_tol=relative_tol,
                                   progress_every=progress_every,
                                   grid_power=grid_power,
                                   noise_floor_ulp=noise_floor_ulp,
                                   accel=accel, ladder=ladder,
                                   telemetry=telemetry, sentinel=sentinel,
                                   faults=faults)
    if grid_power > 0.0 and bool(sol.escaped):
        sol = solve_aiyagari_egm_labor(C_init, a_grid, s, P, r, w, amin,
                                       sigma=sigma, beta=beta, psi=psi, eta=eta,
                                       tol=tol, max_iter=max_iter,
                                       relative_tol=relative_tol,
                                       progress_every=progress_every,
                                       grid_power=0.0,
                                       noise_floor_ulp=noise_floor_ulp,
                                       accel=accel, ladder=ladder,
                                       telemetry=telemetry, sentinel=sentinel,
                                       faults=faults)
    return sol


def _warm_stage_knobs(ladder, noise_floor_ulp: float):
    """(ladder, noise_floor_ulp) for a multiscale WARM stage: the hot-only
    truncation of the full ladder, stopped at the hot dtype's switch floor.
    A warm stage's product is a prolongation input, not a certified
    solution — polishing it in the wide dtype would buy accuracy the next
    stage's re-convergence immediately discards (the ISSUE-4 "warm stages
    are the natural f32 citizens" wiring)."""
    if ladder is None:
        return None, noise_floor_ulp
    return hot_only(ladder), max(float(noise_floor_ulp),
                                 float(ladder.switch_ulp))


def _host_ladder(a_grid, s, r, w, *, sizes, lo: float, hi: float,
                 grid_power: float, solve_stage) -> EGMSolution:
    """Host-level stage loop shared by the generic-route retry and the
    labor-family ladders: initial guess on the coarsest grid, per-stage
    solve via `solve_stage(C, grid, final)`, analytic prolongation between
    stages (final stage on the CALLER's grid array, bitwise), per-stage
    escape flags OR-ed on device, and one batched scalar fetch at the end.
    One body, so the ladder protocol cannot drift between its host users
    (the fast path is the separately-traced _egm_ladder_fused). The `final`
    flag lets stages pick precision-ladder knobs (_warm_stage_knobs)."""
    from aiyagari_tpu.utils.grids import stage_grid

    dtype = a_grid.dtype
    C = initial_consumption_guess(
        stage_grid(sizes[0], lo, hi, grid_power, dtype), s, r, w).astype(dtype)
    sol = None
    esc = jnp.array(False)
    for i, n in enumerate(sizes):
        final = i == len(sizes) - 1
        g = a_grid if final else stage_grid(n, lo, hi, grid_power, dtype)
        if i > 0:
            C = prolong_power_grid(sol.policy_c, lo, hi, grid_power, n)
        sol = solve_stage(C, g, final)
        esc = esc | sol.escaped
    return _fetch_scalars(dataclasses.replace(sol, escaped=esc))


@partial(jax.jit, static_argnames=("sizes", "lo", "hi", "sigma", "beta",
                                   "tol", "max_iter", "relative_tol",
                                   "progress_every", "grid_power",
                                   "noise_floor_ulp", "egm_kernel", "accel",
                                   "ladder", "telemetry", "sentinel",
                                   "faults"))
def _egm_ladder_fused(a_grid, s, P, r, w, amin, *, sizes, lo: float,
                      hi: float, sigma: float, beta: float, tol: float,
                      max_iter: int, relative_tol: bool, progress_every: int,
                      grid_power: float, noise_floor_ulp: float,
                      egm_kernel: str, accel=None, ladder=None,
                      telemetry=None, sentinel=None,
                      faults=None) -> EGMSolution:
    """The whole fast-path stage ladder traced as ONE device program:
    stage solve -> prolong -> next stage, unrolled over the static `sizes`
    tuple. Why one program: each separately-jitted stage costs a ~100 ms
    dispatch round trip on this image's remote TPU transport plus a fetch
    fence, and the ladder has 4 stages — at the 400k north-star scale that
    overhead was ~45% of the measured 0.54 s wall (round-3 stage timing;
    BENCHMARKS.md). Inside one jit the stages chain on device with no host
    involvement, and XLA owns all intermediate buffers."""
    from aiyagari_tpu.utils.grids import stage_grid

    dtype = a_grid.dtype
    C = initial_consumption_guess(
        stage_grid(sizes[0], lo, hi, grid_power, dtype), s, r, w).astype(dtype)
    sol = None
    esc = jnp.array(False)
    for i, n in enumerate(sizes):
        final = i == len(sizes) - 1
        # The final stage uses the CALLER's grid array (bitwise — the
        # analytic rebuild could differ from the model builder's by an ulp);
        # intermediate grids are rebuilt analytically on device. Under a
        # precision ladder the warm stages run hot-only (f32 citizens,
        # stopped at the switch floor); the final stage runs the full
        # hot->polish ladder (_warm_stage_knobs).
        g = a_grid if final else stage_grid(n, lo, hi, grid_power, dtype)
        st_ladder, st_floor = ((ladder, noise_floor_ulp) if final
                               else _warm_stage_knobs(ladder, noise_floor_ulp))
        if i > 0:
            C = prolong_power_grid(sol.policy_c, lo, hi, grid_power, n)
        # The flight recorder and the failure sentinel ride the FINAL stage
        # only: warm stages are prolongation inputs, not certified
        # solutions, and keeping them recorder-free keeps their programs
        # bit-identical to the telemetry-off ladder. Injected faults hit
        # the final stage too — the certified product is the one the
        # recovery machinery must see fail.
        sol = solve_aiyagari_egm(C, g, s, P, r, w, amin,
                                 sigma=sigma, beta=beta, tol=tol,
                                 max_iter=max_iter,
                                 relative_tol=relative_tol,
                                 progress_every=progress_every,
                                 grid_power=grid_power,
                                 noise_floor_ulp=st_floor,
                                 egm_kernel=egm_kernel, accel=accel,
                                 ladder=st_ladder,
                                 telemetry=telemetry if final else None,
                                 sentinel=sentinel if final else None,
                                 faults=faults if final else None)
        esc = esc | sol.escaped
    return dataclasses.replace(sol, escaped=esc)


def _penultimate_warm_start(a_grid, grid_power: float, solve_coarse):
    """Shared body of the mesh routes' ladder warm starts: converge the
    multiscale ladder's PENULTIMATE stage via `solve_coarse(grid)` and
    prolong its consumption policy to the full grid. Returns None when the
    ladder has a single stage (nothing coarser to solve) or the coarse
    solve escaped — an escape here means the policy is NaN-poisoned and
    would enter the sharded solve as a "warm start" whose NaNs exit its
    loop after one sweep with escaped=False, a silently-converged NaN
    solution; a cold start is the safe fallback."""
    from aiyagari_tpu.utils.grids import stage_grid, stage_sizes

    n_final = int(a_grid.shape[-1])
    lo, hi = _cached_grid_bounds(a_grid)
    sizes = stage_sizes(n_final, LADDER_COARSEST, LADDER_REFINE)
    if len(sizes) < 2:
        return None
    coarse = stage_grid(sizes[-2], lo, hi, grid_power, a_grid.dtype)
    csol = solve_coarse(coarse)
    if bool(csol.escaped):
        return None
    return prolong_power_grid(csol.policy_c, lo, hi, grid_power, n_final)


def ladder_warm_start(a_grid, s, P, r, w, amin, *, sigma: float, beta: float,
                      tol: float, max_iter: int, grid_power: float,
                      relative_tol: bool = False, accel=None, ladder=None):
    """Converge the multiscale ladder's PENULTIMATE stage and prolong its
    consumption policy to the full grid — the warm start the mesh route
    feeds solve_aiyagari_egm_sharded, so the sharded fine solve runs a warm
    handful of sweeps instead of ~290 cold full-size ones (the same nested
    iteration solve_aiyagari_egm_multiscale performs internally). Returns
    None when there is nothing coarser to solve (_penultimate_warm_start).
    Under a precision ladder the whole warm-start product runs hot-only
    (its consumer re-converges and polishes on the fine grid anyway); the
    prolonged policy is cast back to the caller's grid dtype."""
    wl, wf = _warm_stage_knobs(ladder, 0.0)
    C0 = _penultimate_warm_start(
        a_grid, grid_power,
        lambda coarse: solve_aiyagari_egm_multiscale(
            coarse, s, P, r, w, amin, sigma=sigma, beta=beta, tol=tol,
            max_iter=max_iter, grid_power=grid_power,
            relative_tol=relative_tol, noise_floor_ulp=wf, accel=accel,
            ladder=wl))
    return None if C0 is None else C0.astype(a_grid.dtype)


def ladder_warm_start_labor(a_grid, s, P, r, w, amin, *, sigma: float,
                            beta: float, psi: float, eta: float, tol: float,
                            max_iter: int, grid_power: float,
                            relative_tol: bool = False, accel=None,
                            ladder=None):
    """ladder_warm_start for the endogenous-labor family: the penultimate
    stage runs the labor multiscale ladder and only the consumption policy
    is prolonged (the labor/asset policies are closed-form per sweep,
    solve_aiyagari_egm_labor_multiscale's rationale). Feeds
    solve_aiyagari_egm_labor_sharded's warm start in the mesh route."""
    wl, wf = _warm_stage_knobs(ladder, 0.0)
    C0 = _penultimate_warm_start(
        a_grid, grid_power,
        lambda coarse: solve_aiyagari_egm_labor_multiscale(
            coarse, s, P, r, w, amin, sigma=sigma, beta=beta, psi=psi,
            eta=eta, tol=tol, max_iter=max_iter, grid_power=grid_power,
            relative_tol=relative_tol, noise_floor_ulp=wf, accel=accel,
            ladder=wl))
    return None if C0 is None else C0.astype(a_grid.dtype)


def solve_aiyagari_egm_multiscale(a_grid, s, P, r, w, amin, *, sigma: float,
                                  beta: float, tol: float, max_iter: int,
                                  grid_power: float = 2.0,
                                  coarsest: int = LADDER_COARSEST,
                                  refine_factor: int = LADDER_REFINE,
                                  relative_tol: bool = False,
                                  progress_every: int = 0,
                                  noise_floor_ulp: float = 0.0,
                                  egm_kernel: str = "xla",
                                  accel=None, ladder=None,
                                  telemetry=None, sentinel=None,
                                  faults=None) -> EGMSolution:
    """Grid-sequenced EGM: solve on a coarse grid first, prolong the
    consumption policy to each finer grid, and re-converge there.

    Why: the EGM fixed point contracts at rate beta per sweep regardless of
    the starting point's distance, so a cold start at an n-point grid costs
    ~log(d0/tol)/log(1/beta) full-size sweeps (~290 at the shipped
    calibration). Warm-starting from the previous grid's solution cuts d0
    from O(1) to the coarse grid's discretization error, so the expensive
    fine-grid stages run a fraction of the sweeps — the classic multigrid
    nested iteration, and the intended way to reach the BASELINE.json
    north-star scale (400k points) on TPU. Identical fixed point to the
    single-grid solve (same operator, same tolerance on the final grid;
    pinned by test_solvers).

    a_grid must be power-spaced with exponent `grid_power` (the framework's
    builders are; utils/grids.power_grid) so intermediate grids can be
    rebuilt analytically at any resolution. The fast-path ladder is ONE
    jitted device program (_egm_ladder_fused) — no host dispatch between
    stages; the windowed fast path's escape NaN (ops/interp.
    inverse_interp_power_grid) propagates through the remaining stages, the
    per-stage `escaped` flags are OR-ed on device, and one host read at the
    end decides the generic-route retry for the whole ladder (which runs as
    a host-level stage loop — the rare path keeps no fused program).
    """
    from aiyagari_tpu.utils.grids import stage_grid, stage_sizes

    if grid_power <= 0.0:
        # 0.0 is solve_aiyagari_egm's "not power-spaced" sentinel; here it
        # would collapse every stage grid to the top point (t**0 == 1) and
        # poison the prolongation with 0/0 — fail loudly instead, like
        # solve_aiyagari_vfi_multiscale.
        raise ValueError(
            "solve_aiyagari_egm_multiscale requires a power-spaced grid: pass "
            f"its actual spacing exponent as grid_power, got {grid_power}"
        )
    n_final = int(a_grid.shape[-1])
    dtype = a_grid.dtype
    lo, hi = _cached_grid_bounds(a_grid)
    sizes = stage_sizes(n_final, coarsest, refine_factor)

    sol = _egm_ladder_fused(a_grid, s, P, r, w, amin, sizes=tuple(sizes),
                            lo=lo, hi=hi, sigma=sigma, beta=beta, tol=tol,
                            max_iter=max_iter, relative_tol=relative_tol,
                            progress_every=progress_every,
                            grid_power=grid_power,
                            noise_floor_ulp=noise_floor_ulp,
                            egm_kernel=egm_kernel, accel=accel, ladder=ladder,
                            telemetry=telemetry, sentinel=sentinel,
                            faults=faults)
    sol = _fetch_scalars(sol)
    # Retry only arms when some stage's windowed route actually escaped; a
    # NaN distance with escaped=False is genuine divergence and surfaces.
    if bool(sol.escaped):
        def retry_stage(C, g, final):
            st_ladder, st_floor = ((ladder, noise_floor_ulp) if final else
                                   _warm_stage_knobs(ladder, noise_floor_ulp))
            return solve_aiyagari_egm(
                C, g, s, P, r, w, amin, sigma=sigma, beta=beta, tol=tol,
                max_iter=max_iter, relative_tol=relative_tol,
                progress_every=progress_every, grid_power=0.0,
                noise_floor_ulp=st_floor, accel=accel, ladder=st_ladder,
                telemetry=telemetry if final else None,
                sentinel=sentinel if final else None,
                faults=faults if final else None)

        sol = _host_ladder(
            a_grid, s, r, w, sizes=tuple(sizes), lo=lo, hi=hi,
            grid_power=grid_power, solve_stage=retry_stage)
    return sol


def solve_aiyagari_egm_labor_multiscale(a_grid, s, P, r, w, amin, *,
                                        sigma: float, beta: float, psi: float,
                                        eta: float, tol: float, max_iter: int,
                                        grid_power: float = 2.0,
                                        coarsest: int = LADDER_COARSEST,
                                        refine_factor: int = LADDER_REFINE,
                                        relative_tol: bool = False,
                                        progress_every: int = 0,
                                        noise_floor_ulp: float = 0.0,
                                        accel=None, ladder=None,
                                        telemetry=None, sentinel=None,
                                        faults=None) -> EGMSolution:
    """Grid-sequenced EGM for the endogenous-labor family — the same nested
    iteration as solve_aiyagari_egm_multiscale (see its docstring for the
    rationale and escape handling). Only the consumption policy C is
    prolonged across stages: the labor and asset policies are closed-form
    functions of C within each sweep (the intratemporal FOC and the budget
    constraint, ops/egm.egm_step_labor), so (C, l) move jointly without a
    separate labor prolongation. Reference operator:
    Aiyagari_Endogenous_Labor_EGM.m:67-107."""
    from aiyagari_tpu.utils.grids import stage_grid, stage_sizes

    if grid_power <= 0.0:
        raise ValueError(
            "solve_aiyagari_egm_labor_multiscale requires a power-spaced "
            f"grid: pass its actual spacing exponent as grid_power, got {grid_power}"
        )
    n_final = int(a_grid.shape[-1])
    lo, hi = _cached_grid_bounds(a_grid)
    sizes = stage_sizes(n_final, coarsest, refine_factor)

    def run_ladder(fast: bool) -> EGMSolution:
        def stage(C, g, final):
            st_ladder, st_floor = ((ladder, noise_floor_ulp) if final else
                                   _warm_stage_knobs(ladder, noise_floor_ulp))
            return solve_aiyagari_egm_labor(
                C, g, s, P, r, w, amin, sigma=sigma, beta=beta, psi=psi,
                eta=eta, tol=tol, max_iter=max_iter,
                relative_tol=relative_tol, progress_every=progress_every,
                grid_power=grid_power if fast else 0.0,
                noise_floor_ulp=st_floor, accel=accel, ladder=st_ladder,
                telemetry=telemetry if final else None,
                sentinel=sentinel if final else None,
                faults=faults if final else None)

        return _host_ladder(
            a_grid, s, r, w, sizes=tuple(sizes), lo=lo, hi=hi,
            grid_power=grid_power, solve_stage=stage)

    sol = run_ladder(fast=True)
    if bool(sol.escaped):
        sol = run_ladder(fast=False)
    return sol
