"""Distributed Krusell-Smith VFI: the Howard-accelerated [ns, nK, nk]
fixed point (solvers/ks_vfi.py; Krusell_Smith_VFI.m:141-204) with the fine
individual-capital axis sharded across the mesh — the last household
solver without a grid-sharded form (VERDICT round 4 missing #1).

Design — and why it is NOT the ring slab of ks_egm_sharded: the two
solvers put their weight on opposite ends of the table/compute scale.

  * The Aiyagari families shard 40k-400k-point grids where the TABLE is
    the big object — any full-grid collective is the bandwidth story, so
    the ring ships O(nk/D) slabs and the HLO tests pin that nothing
    full-grid-shaped crosses devices.
  * The K-S fine table is [ns, nK, nk] with nk in the hundreds-to-
    thousands: O(ns*nK*nk) = ~256 KB at nk=4,096 f32. The VFI's expensive
    objects are the improvement's candidate scoring — the [ns, nK, nk, nk']
    tensor, O(R*nk^2) VPU work and bytes (1 GB at nk=4,000) — and the
    Howard evaluation's per-sweep pchip re-interpolation, O(R*nk) gathers
    x howard_steps. Sharding the QUERY axis makes both local at 1/D cost;
    replicating the small value table per sweep is ONE tiled all_gather of
    O(R*nk) bytes — microseconds over ICI, amortized against O(R*nk^2/D)
    local compute. A ring slab would save part of those O(R*nk) bytes at
    the price of O(D) latency rounds, per-row positioning, and the escape
    machinery — a poor trade when the table is 1e2-1e3x smaller than the
    candidate tensor it feeds (and it would STILL need a full-range
    candidate scan: the dense argmax ranks every global k', by design —
    the f32 ALM-stability rationale in solve_ks_vfi.improve).

The collective contract is therefore scale-matched, not copied from the
Aiyagari kernels: nothing [*, nk, nk']-shaped ever crosses devices — every
collective operand is O(ns*nK*nk) or smaller (pinned by
tests/test_ks_sharded.py::TestShardedKSVFI::test_no_candidate_tensor_crosses).

Identical fixed point to solve_ks_vfi: the gathered table reproduces the
single-device rows exactly (shard-order concatenation), every local query
then sees the same candidate values, pchip stencils, and golden brackets,
so the trajectory matches at f64 tolerance (test_trajectory_matches) —
no repair/escape semantics are needed at all. Host-level entry — not
callable inside jit. No in-jit progress telemetry (device_progress
callbacks are not supported under shard_map on all backends).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from aiyagari_tpu.parallel.mesh import PartitionSpec as P, shard_map as _shard_map

from aiyagari_tpu.ops.golden import golden_section_max
from aiyagari_tpu.parallel.halo import cached_program, mesh_fingerprint
from aiyagari_tpu.solvers.ks_vfi import (
    KSSolution,
    _alm_next_K_index,
    _expected_value,
    _gather_next_tables,
)
from aiyagari_tpu.utils.utility import crra_utility

__all__ = ["solve_ks_vfi_sharded"]

_KS_VFI_PROGRAMS: dict = {}


def solve_ks_vfi_sharded(mesh, value_init, k_opt_init, B, k_grid, K_grid,
                         P_mat, r_table, w_table, eps_by_state, *,
                         theta: float, beta: float, mu: float, l_bar: float,
                         delta: float, k_min: float, k_max: float,
                         tol: float, max_iter: int, howard_steps: int = 50,
                         improve_every: int = 5, golden_iters: int = 48,
                         relative_tol: bool = True,
                         axis: str = "grid") -> KSSolution:
    """solve_ks_vfi with the fine k-axis sharded over mesh[axis] (module
    docstring). Same improvement cadence, Howard burst, stopping rule, and
    fixed point as the single-device solver; the convergence distance is
    pmax'd so all devices run the while_loop in lockstep."""
    D = int(mesh.shape[axis])
    ns, nK, nk = value_init.shape
    if nk % D:
        raise ValueError(f"mesh axis size {D} must divide the k-grid {nk}")
    dtype = jnp.dtype(value_init.dtype)
    run = _ks_vfi_program(mesh, axis, ns, nK, nk, float(theta), float(beta),
                          float(mu), float(l_bar), float(delta), float(k_min),
                          float(k_max), float(tol), int(max_iter),
                          int(howard_steps), int(improve_every),
                          int(golden_iters), bool(relative_tol), dtype.name)
    value, k_opt, dist, it = run(value_init, k_opt_init, B, k_grid, K_grid,
                                 P_mat, r_table, w_table, eps_by_state)
    dist_h, it_h = jax.device_get((dist, it))
    return KSSolution(value, k_opt, it_h, dist_h)


def _ks_vfi_program(mesh, axis: str, ns: int, nK: int, nk: int, theta: float,
                    beta: float, mu: float, l_bar: float, delta: float,
                    k_min: float, k_max: float, tol: float, max_iter: int,
                    howard_steps: int, improve_every: int, golden_iters: int,
                    relative_tol: bool, dtype_name: str):
    dtype = jnp.dtype(dtype_name)

    def build():
        def local(v0, k0, B_coef, k_loc, K_grid, Pm, r_table, w_table,
                  eps_by_state):
            labor_endow = eps_by_state * l_bar + (1.0 - eps_by_state) * mu
            resources = (
                (r_table + 1.0 - delta)[:, :, None] * k_loc[None, None, :]
                + (w_table * labor_endow[:, None])[:, :, None]
            )                                              # [ns, nK, L]
            Kp_idx = _alm_next_K_index(B_coef, K_grid, ns)
            # The global knot vector, reassembled in shard order — shared
            # by the pchip stencils and the candidate axis.
            k_full = jax.lax.all_gather(k_loc, axis, axis=0, tiled=True)

            def gathered_tables(v_loc):
                v_glob = jax.lax.all_gather(v_loc, axis, axis=2, tiled=True)
                return _gather_next_tables(v_glob, Kp_idx, k_full)

            def bellman_at(kp, V_next, slopes):
                EV = _expected_value(kp, V_next, slopes, Pm, k_full)
                c = jnp.maximum(resources - kp, 1e-10)
                return crra_utility(c, theta) + beta * EV

            def improve(v_loc, k_opt_loc):
                # The single-device two-phase maximization verbatim
                # (solve_ks_vfi.improve rationale), with only the QUERY
                # axis local: the [ns, nK, L, nk'] candidate tensor never
                # leaves the device.
                V_next, slopes = gathered_tables(v_loc)
                EV_grid = jnp.einsum(
                    "sp,sKpk->sKk", Pm, V_next,
                    precision=jax.lax.Precision.HIGHEST)   # [ns, nK, nk']
                c_cand = resources[:, :, :, None] - k_full[None, None, None, :]
                feas = (c_cand > 0.0) & (k_full[None, None, None, :] <= k_max)
                u = crra_utility(jnp.maximum(c_cand, 1e-10), theta)
                q = jnp.where(feas, u + beta * EV_grid[:, :, None, :],
                              jnp.array(-jnp.inf, dtype))
                j_star = jnp.argmax(q, axis=-1)            # [ns, nK, L]
                if golden_iters <= 0:
                    return k_full[j_star]
                f = lambda kp: bellman_at(kp, V_next, slopes)
                lo_r = jnp.maximum(k_full[jnp.maximum(j_star - 1, 0)], k_min)
                hi_r = jnp.minimum(
                    jnp.minimum(k_full[jnp.minimum(j_star + 1, nk - 1)],
                                resources),
                    k_max)
                hi_r = jnp.maximum(hi_r, lo_r)
                return golden_section_max(f, lo_r, hi_r,
                                          n_iters=golden_iters)

            def howard(v_loc, k_opt_loc):
                def sweep(v, _):
                    V_next, slopes = gathered_tables(v)
                    return bellman_at(k_opt_loc, V_next, slopes), None

                v_loc, _ = jax.lax.scan(sweep, v_loc, None,
                                        length=howard_steps)
                return v_loc

            def cond(carry):
                _, _, dist, it = carry
                return (dist >= tol) & (it < max_iter)

            def body(carry):
                value, k_opt, _, it = carry
                k_opt = jax.lax.cond(
                    it % improve_every == 0,
                    lambda: improve(value, k_opt),
                    lambda: k_opt,
                )
                value_new = howard(value, k_opt)
                diff = jnp.abs(value_new - value)
                d_loc = (jnp.max(diff / (jnp.abs(value) + 1e-10))
                         if relative_tol else jnp.max(diff))
                dist = jax.lax.pmax(d_loc, axis)
                return value_new, k_opt, dist, it + 1

            init = (v0, k0, jnp.array(jnp.inf, dtype), jnp.int32(0))
            return jax.lax.while_loop(cond, body, init)

        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=(P(None, None, axis), P(None, None, axis), P(),
                      P(axis), P(), P(), P(), P(), P()),
            out_specs=(P(None, None, axis), P(None, None, axis), P(), P()),
        ))

    key = mesh_fingerprint(mesh, axis) + (ns, nK, nk, theta, beta, mu,
                                          l_bar, delta, k_min, k_max, tol,
                                          max_iter, howard_steps,
                                          improve_every, golden_iters,
                                          relative_tol, dtype_name)
    return cached_program(_KS_VFI_PROGRAMS, key, build)
