"""Distributed Krusell-Smith EGM solver: the [ns, nK, nk] policy fixed
point under one `jax.shard_map` program with the FINE individual-capital
axis sharded across the mesh and the endogenous knots resident per device.

This generalizes the ring-redistribution machinery (parallel/ring.py) from
the Aiyagari families' linear/value interpolation to the K-S EGM's
sort/mask/pchip re-interpolation (Krusell_Smith_EGM.m:192-198; SURVEY.md
§2.4(1) — the last solver family without a grid-sharded form). Per sweep:

  * the Euler expectation, inversion, and endogenous-grid back-out are
    elementwise in k' — local to each device's [ns, nK, nk/D] shard (the
    next-period policy slice k_opt[s', K'_idx, :] is a row pick in the
    tiny (s, K) table, local along k);
  * one ring rotation (parallel/ring.ring_slab_assemble) gives each of
    the ns*nK rows an O(nk/D) contiguous slab of the global endogenous
    knots, positioned by the exact psum-telescoped bracket starts;
  * each device then runs the SAME masked-pchip kernel as the
    single-device solver (ops/interp.masked_pchip_interp) against its
    slab, rolled so the slab's valid run sits at index 0 — the exogenous
    re-interpolation values are the analytic power grid, so only the knot
    channel rides the ring;
  * O(D) collectives: the bracket-start psum, the cummax-prefix tails
    all_gather, and the pmax'd sup-norm/escape reductions.

Monotonicity note: the single-device solver SORTS the endogenous grid
(the reference's insurance at Krusell_Smith_EGM.m:192); here the
cross-device repair is a cummax (exact no-op when the grid is monotone,
which it is in exact arithmetic — consumption is increasing in k'), so
the two routes agree wherever the endogenous grid is genuinely monotone
(pinned at f64 by tests/test_ks_sharded.py). The f32 behavior is
MEASURED, not hypothesized (round 5, test_f32_tie_divergence_bounded):
at this calibration the raw f32 endogenous grid contains NO strict
rounding inversions — every backout stage is a monotone float evaluation
of monotone inputs, which rounds weakly monotonically — but 64-160 TIED
knot pairs per sweep (nk=1024-2048, the power-7 flat bottom collapsing
below f32 resolution). On ties both repairs keep the knot values
unchanged and differ only in which tied knot's exogenous y-value the
pchip bracket reads; the converged policies diverge by at most 6e-3
absolute on k in [0, 1000] (~6e-6 of the grid span), the Euler-sum
reassociation amplified through ~430 f32 sweeps. The test bounds this
envelope at 2e-5 of the span.

Escape contract: a slab too small for a row's bracket range (or a pchip
stencil reaching past a truncated slab) NaN-poisons the solution and
raises `escaped`, exactly as the Aiyagari sharded solvers; callers fall
back to the single-device solve_ks_egm.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from aiyagari_tpu.parallel.mesh import PartitionSpec as P, shard_map as _shard_map

from aiyagari_tpu.ops.interp import masked_pchip_interp
from aiyagari_tpu.parallel.halo import cached_program, mesh_fingerprint
from aiyagari_tpu.parallel.ring import ring_slab_assemble
from aiyagari_tpu.solvers.ks_vfi import KSSolution, _alm_next_K_index
from aiyagari_tpu.utils.utility import crra_marginal, crra_marginal_inverse

__all__ = ["ks_ring_slab_size", "solve_ks_egm_sharded"]

_KS_EGM_PROGRAMS: dict = {}

# The pchip stencil needs this many knots of slack between any query's
# bracket and a truncated slab end (d[idx] and d[idx+1] read knots
# idx-1..idx+2), and the bracket-start pad must cover the same stencil on
# the low side.
_STENCIL = 3


def ks_ring_slab_size(nk: int, D: int, capacity: float, pad: int) -> int:
    """Per-device slab length for the K-S ring: capacity shards plus the
    bracket pad and pchip stencil margins, capped at nk + pad (a slab
    covering the whole row plus its low pad cannot escape and needs no
    cap games — at the K-S fine grids, 1k-4k points, that degenerate case
    is still far below any memory concern). No 512-block rounding: unlike
    the windowed Aiyagari kernels this slab feeds a dense local pchip, so
    block granularity buys nothing at these row lengths."""
    L = nk // D
    B = int(capacity * L) + 2 * pad + 2 * _STENCIL
    return min(max(B, L + 2 * pad), nk + pad)


def solve_ks_egm_sharded(mesh, k_opt_init, B_coef, k_grid, K_grid, P_mat,
                         r_table, w_table, eps_by_state, z_by_state,
                         L_by_state, alpha: float, *, theta: float,
                         beta: float, mu: float, l_bar: float, delta: float,
                         k_min: float, k_max: float, tol: float,
                         max_iter: int, grid_power: float,
                         double_alm: bool = False, capacity: float = 2.0,
                         pad: int = 8, axis: str = "grid") -> KSSolution:
    """solve_ks_egm with the fine k-axis sharded over mesh[axis] (module
    docstring). Same stopping rule and fixed point as the single-device
    solver; `grid_power` must be k_grid's actual spacing exponent (the
    K-S power-7 law, utils/grids.ks_k_grid) — the slab positioning uses
    the analytic query form. Host-level entry — not callable inside jit.

    Returns (KSSolution, escaped): KSSolution has no escape field (the
    single-device K-S solvers cannot escape), so the flag rides alongside;
    on escape the solution is NaN-poisoned and the caller falls back to
    the unsharded solve_ks_egm."""
    if grid_power <= 0.0:
        raise ValueError(
            "solve_ks_egm_sharded requires a power-spaced k_grid: pass its "
            f"actual spacing exponent as grid_power, got {grid_power}")
    D = int(mesh.shape[axis])
    ns, nK, nk = k_opt_init.shape
    if nk % D:
        raise ValueError(f"mesh axis size {D} must divide the k-grid {nk}")
    if pad < _STENCIL:
        raise ValueError(
            f"pad must be >= {_STENCIL} (the pchip stencil), got {pad}")
    if capacity < 1.0:
        raise ValueError(f"capacity must be >= 1.0, got {capacity}")
    dtype = k_opt_init.dtype
    run = _ks_egm_program(mesh, axis, ns, nK, nk, float(grid_power),
                          float(capacity), int(pad), float(theta),
                          float(beta), float(mu), float(l_bar), float(delta),
                          float(k_min), float(k_max), float(tol),
                          int(max_iter), bool(double_alm),
                          jnp.dtype(dtype).name)
    k_opt, dist, it, esc = run(k_opt_init, B_coef, k_grid, K_grid, P_mat,
                               r_table, w_table, eps_by_state)
    esc_h, dist_h, it_h = jax.device_get((esc, dist, it))
    return KSSolution(jnp.zeros_like(k_opt), k_opt, it_h, dist_h), bool(esc_h)


def _ks_egm_program(mesh, axis: str, ns: int, nK: int, nk: int, power: float,
                    capacity: float, pad: int, theta: float, beta: float,
                    mu: float, l_bar: float, delta: float, k_min: float,
                    k_max: float, tol: float, max_iter: int,
                    double_alm: bool, dtype_name: str):
    D = int(mesh.shape[axis])
    L = nk // D
    dtype = jnp.dtype(dtype_name)
    B = ks_ring_slab_size(nk, D, capacity, pad)
    span = k_max - k_min
    R = ns * nK
    neg = jnp.array(-jnp.inf, dtype)

    def gk_of(i):
        # The analytic K-S spacing law (utils/grids.ks_k_grid).
        return k_min + span * (i.astype(dtype) / (nk - 1)) ** power

    def build():
        def local(k0, B_coef, k_loc, K_grid, Pm, r_table, w_table,
                  eps_by_state):
            dev = jax.lax.axis_index(axis)
            labor_endow = eps_by_state * l_bar + (1.0 - eps_by_state) * mu

            Kp_idx = _alm_next_K_index(B_coef, K_grid, ns)         # [ns, nK]
            Kp_val = K_grid[Kp_idx]
            zp_index = jnp.arange(ns) % 2
            if double_alm:
                from aiyagari_tpu.solvers.ks_vfi import alm_predict

                Kpp = alm_predict(B_coef, Kp_val[:, :, None],
                                  zp_index[None, None, :])
                Kpp = jnp.clip(Kpp, K_grid[0], K_grid[-1])
                Knext_idx = jnp.argmin(
                    jnp.abs(K_grid[None, None, None, :] - Kpp[..., None]),
                    axis=-1).astype(jnp.int32)
            else:
                Knext_idx = jnp.broadcast_to(Kp_idx[:, :, None],
                                             (ns, nK, ns))
            r_next_tab = r_table[jnp.arange(ns)[None, None, :], Knext_idx]
            w_next_tab = w_table[jnp.arange(ns)[None, None, :], Knext_idx]

            # Every device's first query, analytically, for the psum'd
            # bracket starts (ring step 1; ulp drift vs the caller's grid
            # array is absorbed by pad).
            e = jnp.arange(D)
            q_first_all = gk_of(e * L)                              # [D]

            def sweep(k_opt):
                def euler_row(s, K_i):
                    def per_next(sp):
                        rn = r_next_tab[s, K_i, sp]
                        wn = w_next_tab[s, K_i, sp]
                        kp_next = k_opt[sp, Knext_idx[s, K_i, sp], :]
                        res_next = (1.0 + rn - delta) * k_loc \
                            + wn * labor_endow[sp]
                        c_next = jnp.maximum(res_next - kp_next, 1e-8)
                        return Pm[s, sp] * (1.0 + rn - delta) \
                            * crra_marginal(c_next, theta)

                    expected = jnp.sum(jax.vmap(per_next)(jnp.arange(ns)),
                                       axis=0)                      # [L]
                    c = crra_marginal_inverse(beta * expected, theta)
                    k_endo = (c + k_loc - w_table[s, K_i] * labor_endow[s]) \
                        / (1.0 + r_table[s, K_i] - delta)
                    return k_endo

                s_idx, K_idx = jnp.meshgrid(jnp.arange(ns), jnp.arange(nK),
                                            indexing="ij")
                k_endo = jax.vmap(euler_row)(s_idx.ravel(), K_idx.ravel())
                # [R, L] local endogenous-knot shards.

                # Global cummax (the sharded form of the reference's sort —
                # module docstring): local cummax + cross-device prefix.
                k_endo = jax.lax.cummax(k_endo, axis=1)
                tails = jax.lax.all_gather(k_endo[:, -1], axis)     # [D, R]
                mask = (jnp.arange(D) < dev)[:, None]
                pref = jnp.max(jnp.where(mask, tails, neg), axis=0)
                k_endo = jnp.maximum(k_endo, pref[:, None])

                # Exact global bracket starts (valid-count psum rides along
                # for the degenerate-slab escape).
                cnt_part = jnp.sum(
                    k_endo[:, None, :] < q_first_all[None, :, None],
                    axis=-1).astype(jnp.int32)                      # [R, D]
                nv_part = jnp.sum(
                    (k_endo >= k_min) & (k_endo <= k_max),
                    axis=-1).astype(jnp.int32)                      # [R]
                c_all, nv_glob = jax.lax.psum((cnt_part, nv_part), axis)
                s_start = c_all[:, dev] - pad                       # [R]

                buf = ring_slab_assemble(k_endo[None], s_start, B=B,
                                         n_k=nk, axis=axis, D=D)[0]  # [R, B]

                def interp_row(bufr, s0, nvg):
                    # Valid run inside the slab (contiguous: the knots are
                    # globally monotone and the out-of-range sentinels are
                    # ±inf, so invalids form a prefix and a suffix).
                    valid = (bufr >= k_min) & (bufr <= k_max)
                    nv = jnp.sum(valid).astype(jnp.int32)
                    o = jnp.argmax(valid).astype(jnp.int32)
                    # Roll the valid run to index 0 and re-sentinel the
                    # tail: the slab then looks exactly like the
                    # single-device sorted/masked row to masked_pchip.
                    xs = jnp.roll(bufr, -o)
                    xs = jnp.where(jnp.arange(B) < nv, xs, jnp.inf)
                    # Exogenous values of the valid knots: the analytic
                    # grid at their RAW global positions.
                    ys = gk_of(jnp.clip(s0 + o + jnp.arange(B), 0, nk - 1))
                    out = masked_pchip_interp(xs, ys, jnp.maximum(nv, 2),
                                              k_loc)
                    # Escapes: (a) the slab's valid run is truncated by the
                    # slab top while global knots continue, and some
                    # query's bracket (or its pchip stencil) reaches the
                    # truncation; (b) the slab misses so much of the valid
                    # run that fewer than a stencil's worth of knots
                    # remain while the global run is larger.
                    cnt_loc = jnp.sum(bufr[None, :] < k_loc[:, None],
                                      axis=-1).astype(jnp.int32)    # [L]
                    truncated = (o + nv >= B) & (s0 + B < nk)
                    esc = truncated & (jnp.max(cnt_loc) + _STENCIL >= o + nv)
                    esc = esc | ((nv < 2 + _STENCIL) & (nvg > nv))
                    return jnp.clip(out, k_min, k_max), esc

                out, esc_rows = jax.vmap(interp_row)(buf, s_start, nv_glob)
                escaped = jax.lax.pmax(
                    jnp.any(esc_rows).astype(jnp.int32), axis)
                out = jnp.where(escaped > 0, jnp.nan, out)
                return out.reshape(ns, nK, L), escaped

            def cond(carry):
                _, dist, it, _ = carry
                return (dist >= tol) & (it < max_iter)

            def body(carry):
                k_opt, _, it, esc = carry
                k_new, esc_new = sweep(k_opt)
                dist = jax.lax.pmax(jnp.max(jnp.abs(k_new - k_opt)), axis)
                return k_new, dist, it + 1, esc | (esc_new > 0)

            init = (k0, jnp.array(jnp.inf, dtype), jnp.int32(0),
                    jnp.array(False))
            return jax.lax.while_loop(cond, body, init)

        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=(P(None, None, axis), P(), P(axis), P(), P(), P(),
                      P(), P()),
            out_specs=(P(None, None, axis), P(), P(), P()),
        ))

    key = mesh_fingerprint(mesh, axis) + (ns, nK, nk, power, capacity, pad,
                                          theta, beta, mu, l_bar, delta,
                                          k_min, k_max, tol, max_iter,
                                          double_alm, dtype_name)
    return cached_program(_KS_EGM_PROGRAMS, key, build)
