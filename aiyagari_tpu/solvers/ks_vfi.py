"""Krusell-Smith household solver: Howard-accelerated value-function iteration
with batched golden-section policy improvement.

The reference (Krusell_Smith_VFI.m:141-204) runs 1,600 scalar fminbnd
optimizations every 5th sweep and 50 interpreted Howard evaluation sweeps per
iteration, refreshing 16 pchip interpolants each sweep. Here the whole fixed
point is one XLA program: the improvement step is a vectorized golden-section
search over all (state, K, k) points at once, Howard evaluation is a lax.scan,
and pchip slope tables are recomputed as batched kernels.

Array layout: value/policy arrays are [ns, nK, nk] with the fine k axis last
(TPU lanes dimension).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.progress import device_progress
from aiyagari_tpu.ops.golden import golden_section_max
from aiyagari_tpu.ops.interp import pchip_interp, pchip_slopes
from aiyagari_tpu.utils.utility import crra_utility

__all__ = ["KSSolution", "alm_predict", "solve_ks_vfi"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class KSSolution:
    """Converged K-S household solution on the [ns, nK, nk] grid."""

    value: jax.Array        # [ns, nK, nk] (all-zeros for the EGM solver)
    k_opt: jax.Array        # [ns, nK, nk] capital policy
    iterations: jax.Array
    distance: jax.Array


def alm_predict(B, K, z_index):
    """K' = exp(b0 + b1 log K) with regime-(z) coefficients B=[b0_g,b1_g,b0_b,b1_b]
    (Krusell_Smith_VFI.m:335-340, incl. the log(max(K,1e-8)) guard)."""
    logK = jnp.log(jnp.maximum(K, 1e-8))
    b0 = jnp.where(z_index == 0, B[0], B[2])
    b1 = jnp.where(z_index == 0, B[1], B[3])
    return jnp.exp(b0 + b1 * logK)


def _alm_next_K_index(B, K_grid, ns: int):
    """Per-(state, K) nearest-grid-point index of the ALM-predicted K'
    (the reference's clamp + snap at Krusell_Smith_VFI.m:340-343).
    Returns [ns, nK] int32. State ordering: z_index = s % 2."""
    z_index = jnp.arange(ns) % 2                              # [ns]
    Kp = alm_predict(B, K_grid[None, :], z_index[:, None])    # [ns, nK]
    Kp = jnp.clip(Kp, K_grid[0], K_grid[-1])
    return jnp.argmin(jnp.abs(K_grid[None, None, :] - Kp[:, :, None]), axis=-1).astype(jnp.int32)


def _gather_next_tables(value, Kp_idx, k_grid):
    """V_next[s, K, s', :] = value[s', Kp_idx[s, K], :] plus its pchip slope
    table — the batched analogue of refreshing the 16 V_interp interpolants
    (Krusell_Smith_VFI.m:128-135,186-191). Slopes are computed once per
    distinct (s', K') row of `value` (ns*nK rows) and gathered alongside,
    not recomputed per (s, K, s') combination."""
    flat = value.reshape(-1, value.shape[-1])
    d = jax.vmap(pchip_slopes, in_axes=(None, 0))(k_grid, flat).reshape(value.shape)
    V_next = jnp.moveaxis(value[:, Kp_idx, :], 0, 2)   # [ns, nK, s', nk]
    slopes = jnp.moveaxis(d[:, Kp_idx, :], 0, 2)
    return V_next, slopes


def _expected_value(kp, V_next, slopes, P, k_grid):
    """EV[s,K,k] = sum_s' P[s,s'] * pchip(k_grid, V_next[s,K,s',:], kp[s,K,k])
    with queries clamped to the grid (Krusell_Smith_VFI.m:346-349)."""

    def per_point(kp_row, V_row, d_row, P_row):
        # kp_row [nk]; V_row/d_row [ns, nk]; P_row [ns]
        vals = jax.vmap(lambda v, d: pchip_interp(k_grid, v, kp_row, d))(V_row, d_row)
        return jnp.matmul(P_row, vals, precision=jax.lax.Precision.HIGHEST)  # [nk]

    return jax.vmap(jax.vmap(per_point, in_axes=(0, 0, 0, None)), in_axes=(0, 0, 0, 0))(
        kp, V_next, slopes, P
    )


@partial(jax.jit, static_argnames=("theta", "beta", "mu", "l_bar", "tol", "max_iter",
                                   "howard_steps", "improve_every", "golden_iters",
                                   "relative_tol", "progress_every"))
def solve_ks_vfi(value_init, k_opt_init, B, k_grid, K_grid, P, r_table, w_table,
                 eps_by_state, *, theta: float, beta: float, mu: float, l_bar: float,
                 delta: float, k_min: float, k_max: float, tol: float, max_iter: int,
                 howard_steps: int = 50, improve_every: int = 5,
                 golden_iters: int = 48, relative_tol: bool = True,
                 progress_every: int = 0) -> KSSolution:
    """Howard-accelerated VFI given ALM coefficients B.

    Matches Krusell_Smith_VFI.m:141-204: policy improvement every
    `improve_every` iterations, `howard_steps` evaluation sweeps per
    iteration, relative sup-norm convergence (:195). The improvement step
    replaces the reference's per-point fminbnd over k' in
    [k_min, min(resources, k_max)] with a dense argmax over the k_grid
    candidates followed by `golden_iters` golden-section iterations inside
    the winning cell's brackets — same continuous within-cell semantics,
    but the candidate ranking is a direct value comparison, which keeps the
    policy reproducible between ALM iterations in low precision (rationale
    in improve(); golden_iters <= 0 returns the pure grid policy, which is
    too coarse for this power-7 grid — K collapses — so keep it > 0).
    """
    ns, nK, nk = value_init.shape

    # Resources: (r + 1 - delta) k + w (eps l_bar + (1-eps) mu). The reference
    # includes the mu term in the improvement-step resources (:152-153) but not
    # in bellman_value's consumption (:355); mu=0 makes them identical, and we
    # use the consistent form everywhere (SURVEY.md §3.6 quirk 6).
    labor_endow = eps_by_state * l_bar + (1.0 - eps_by_state) * mu       # [ns]
    resources = (
        (r_table + 1.0 - delta)[:, :, None] * k_grid[None, None, :]
        + (w_table * labor_endow[:, None])[:, :, None]
    )                                                                     # [ns, nK, nk]
    Kp_idx = _alm_next_K_index(B, K_grid, ns)                             # [ns, nK]

    def bellman_at(kp, V_next, slopes):
        EV = _expected_value(kp, V_next, slopes, P, k_grid)
        c = jnp.maximum(resources - kp, 1e-10)                            # :355-359
        return crra_utility(c, theta) + beta * EV

    def improve(value, k_opt):
        # Two-phase maximization replacing full-range golden section. Phase
        # 1: dense argmax over the k_grid candidates — at grid knots the
        # pchip-interpolated continuation IS the value table, so EV_grid is
        # one [ns,ns]x[ns,nK,nk] contraction and the whole [ns,nK,nk,nk]
        # score tensor is ~640 KB. Phase 2: one golden-section refine inside
        # the winning cell's brackets, where fminbnd's continuous semantics
        # live. Why not full-range golden (measured, f32, reference scale):
        # near the optimum the objective is flat below f32 resolution, the
        # continuous maximizer jitters by whole cells between ALM
        # iterations, and 1,100 simulation steps amplify that into ~2e-2
        # noise in the regression coefficients — the ALM fixed point then
        # never reaches the reference's 1e-6 tolerance. Grid candidates
        # ranked by direct value comparison bound the jitter at sub-cell
        # scale (same cure as solve_aiyagari_vfi_continuous).
        V_next, slopes = _gather_next_tables(value, Kp_idx, k_grid)
        EV_grid = jnp.einsum(
            "sp,sKpk->sKk", P, V_next, precision=jax.lax.Precision.HIGHEST,
        )                                                                  # [ns, nK, nk']
        c_cand = resources[:, :, :, None] - k_grid[None, None, None, :]    # [ns,nK,nk,nk']
        feas = (c_cand > 0.0) & (k_grid[None, None, None, :] <= k_max)
        u = crra_utility(jnp.maximum(c_cand, 1e-10), theta)
        q = jnp.where(feas, u + beta * EV_grid[:, :, None, :],
                      jnp.array(-jnp.inf, value.dtype))
        j_star = jnp.argmax(q, axis=-1)                                    # [ns, nK, nk]

        if golden_iters <= 0:
            # Pure grid policy: knot values are exactly reproducible across
            # ALM iterations (no within-cell f32 flatness jitter at all).
            return k_grid[j_star]

        f = lambda kp: bellman_at(kp, V_next, slopes)
        lo_r = jnp.maximum(k_grid[jnp.maximum(j_star - 1, 0)], k_min)
        hi_r = jnp.minimum(
            jnp.minimum(k_grid[jnp.minimum(j_star + 1, nk - 1)], resources),
            k_max,
        )                                                                  # :159
        hi_r = jnp.maximum(hi_r, lo_r)
        return golden_section_max(f, lo_r, hi_r, n_iters=golden_iters)

    def howard(value, k_opt):
        def sweep(v, _):
            V_next, slopes = _gather_next_tables(v, Kp_idx, k_grid)
            return bellman_at(k_opt, V_next, slopes), None

        value, _ = jax.lax.scan(sweep, value, None, length=howard_steps)
        return value

    def cond(carry):
        _, _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        value, k_opt, _, it = carry
        k_opt = jax.lax.cond(
            it % improve_every == 0,
            lambda: improve(value, k_opt),
            lambda: k_opt,
        )
        value_new = howard(value, k_opt)
        diff = jnp.abs(value_new - value)
        # Relative sup-norm is the reference's criterion (Krusell_Smith_VFI.m:195).
        dist = jnp.max(diff / (jnp.abs(value) + 1e-10)) if relative_tol else jnp.max(diff)
        device_progress("ks_vfi", it + 1, dist, every=progress_every)
        return value_new, k_opt, dist, it + 1

    init = (value_init, k_opt_init, jnp.array(jnp.inf, value_init.dtype), jnp.int32(0))
    value, k_opt, dist, it = jax.lax.while_loop(cond, body, init)
    return KSSolution(value, k_opt, it, dist)
