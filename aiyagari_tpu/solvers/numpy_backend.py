"""Pure-NumPy reference backend: the framework's own CPU implementation of the
Aiyagari solvers, simulator, and GE bisection.

Purpose (BASELINE.md "denominator policy"): the reference publishes no
performance numbers, so TPU speedups are reported against this implementation
measured at the reference's problem scales. It is also the oracle for
backend-equivalence tests (same math, no JAX) — kept fully vectorized so the
baseline is honest, just un-jitted and host-bound.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from aiyagari_tpu.config import AiyagariConfig, EquilibriumConfig, SimConfig, SolverConfig
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r
from aiyagari_tpu.utils.grids import aiyagari_asset_bounds, aiyagari_asset_grid
from aiyagari_tpu.utils.markov import (
    discretize_income,
    normalized_labor,
    stationary_distribution,
)

__all__ = [
    "aiyagari_arrays_numpy",
    "vfi_numpy",
    "egm_numpy",
    "vfi_labor_numpy",
    "egm_labor_numpy",
    "simulate_numpy",
    "solve_equilibrium_numpy",
]


def aiyagari_arrays_numpy(cfg: AiyagariConfig):
    l_grid, P = discretize_income(cfg.income)
    pi = stationary_distribution(P)
    s, labor_raw = normalized_labor(l_grid, pi)
    amin, _ = aiyagari_asset_bounds(cfg, s_min=float(s[0]))
    a_grid = aiyagari_asset_grid(cfg, s_min=float(s[0]))
    return a_grid, s, P, pi, labor_raw, amin


def _crra(c, sigma):
    if sigma == 1.0:
        return np.log(c)
    return (c ** (1.0 - sigma) - 1.0) / (1.0 - sigma)


def vfi_numpy(v, a_grid, s, P, r, w, *, sigma, beta, tol, max_iter):
    """Vectorized NumPy VFI (Aiyagari_VFI.m:65-90)."""
    N, na = len(s), len(a_grid)
    coh = (1.0 + r) * a_grid[None, :] + w * s[:, None]
    c = coh[:, :, None] - a_grid[None, None, :]
    with np.errstate(invalid="ignore", divide="ignore"):
        u = np.where(c > 0.0, _crra(np.where(c > 0.0, c, 1.0), sigma), -np.inf)
    it = 0
    for it in range(1, max_iter + 1):
        EV = beta * (P @ v)
        q = u + EV[:, None, :]
        v_new = q.max(axis=2)
        idx = q.argmax(axis=2)
        dist = np.max(np.abs(v_new - v))
        v = v_new
        if dist < tol:
            break
    policy_k = a_grid[idx]
    policy_c = coh - policy_k
    return v, idx, policy_k, policy_c, np.ones_like(policy_k), it


def egm_numpy(C, a_grid, s, P, r, w, amin, *, sigma, beta, tol, max_iter):
    """Vectorized NumPy EGM (Aiyagari_EGM.m:74-110)."""
    it = 0
    policy_k = np.zeros_like(C)
    for it in range(1, max_iter + 1):
        RHS = beta * (1.0 + r) * (P @ C ** (-sigma))
        c_next = RHS ** (-1.0 / sigma)
        a_hat = (c_next + a_grid[None, :] - w * s[:, None]) / (1.0 + r)
        for j in range(len(s)):
            policy_k[j] = np.interp(a_grid, a_hat[j], a_grid)
            lo = a_hat[j, 0]
            below = a_grid < lo
            # np.interp clamps; extend the bottom linearly like
            # interp1(...,'extrap'). Above the last endogenous knot the
            # policy is truncated at the grid top instead — the discrete
            # VFI choice set, matching ops/egm.egm_step (where unbounded
            # extrapolation is an f32 stability hazard at fine grids).
            sl_lo = (a_grid[1] - a_grid[0]) / (a_hat[j, 1] - a_hat[j, 0])
            policy_k[j, below] = a_grid[0] + (a_grid[below] - lo) * sl_lo
        policy_k = np.clip(policy_k, amin, a_grid[-1])
        C_new = (1.0 + r) * a_grid[None, :] + w * s[:, None] - policy_k
        dist = np.max(np.abs(C_new - C))
        C = C_new
        if dist < tol:
            break
    return C, policy_k, np.ones_like(C), it


def vfi_labor_numpy(v, a_grid, labor_grid, s, P, r, w, *, sigma, beta, psi, eta, tol, max_iter):
    """Vectorized NumPy endogenous-labor VFI (Aiyagari_Endogenous_Labor_VFI.m:64-122)."""
    N, na = len(s), len(a_grid)
    nl = len(labor_grid)
    disu = psi * labor_grid ** (1.0 + eta) / (1.0 + eta)
    it = 0
    for it in range(1, max_iter + 1):
        EV = beta * (P @ v)
        best = np.full((N, na), -np.inf)
        best_a = np.zeros((N, na), np.int64)
        best_l = np.zeros((N, na), np.int64)
        for li in range(nl):
            coh = (1.0 + r) * a_grid[None, :] + w * labor_grid[li] * s[:, None]
            c = coh[:, :, None] - a_grid[None, None, :]
            u = np.where(c > 0.0, _crra(np.where(c > 0.0, c, 1.0), sigma), -np.inf) - disu[li]
            q = u + EV[:, None, :]
            m = q.max(axis=2)
            mi = q.argmax(axis=2)
            take = m > best
            best = np.where(take, m, best)
            best_a = np.where(take, mi, best_a)
            best_l = np.where(take, li, best_l)
        dist = np.max(np.abs(best - v))
        v = best
        if dist < tol:
            break
    policy_k = a_grid[best_a]
    policy_l = labor_grid[best_l]
    policy_c = (1.0 + r) * a_grid[None, :] + w * s[:, None] * policy_l - policy_k
    return v, best_a, policy_k, policy_c, policy_l, it


def egm_labor_numpy(C, a_grid, s, P, r, w, amin, *, sigma, beta, psi, eta, tol, max_iter):
    """Vectorized NumPy endogenous-labor EGM (Aiyagari_Endogenous_Labor_EGM.m:67-107).

    Keeps the reference's linear extrapolation of g_c below the first
    endogenous knot (stable in f64 at reference scale); the JAX kernel
    (ops/egm.egm_step_labor) instead solves the constrained static problem
    there exactly — the two backends agree on the grid interior only.
    """
    it = 0
    policy_k = np.zeros_like(C)
    policy_l = np.zeros_like(C)
    for it in range(1, max_iter + 1):
        RHS = beta * (1.0 + r) * (P @ C ** (-sigma))
        c_next = RHS ** (-1.0 / sigma)
        ws = w * s[:, None]
        l_endo = (ws * c_next ** (-sigma) / psi) ** (1.0 / eta)
        a_hat = (c_next + a_grid[None, :] - ws * l_endo) / (1.0 + r)
        g_c = np.empty_like(C)
        for j in range(len(s)):
            # np.interp clamps at both ends; extend the bottom linearly like
            # interp1(...,'extrap'), keep the nearest-value top (matches
            # ops/egm.egm_step_labor's grid-top discipline).
            g_c[j] = np.interp(a_grid, a_hat[j], c_next[j])
            lo = a_hat[j, 0]
            below = a_grid < lo
            sl_lo = (c_next[j, 1] - c_next[j, 0]) / (a_hat[j, 1] - a_hat[j, 0])
            g_c[j, below] = c_next[j, 0] + (a_grid[below] - lo) * sl_lo
        g_c = np.where(a_grid[None, :] < amin, amin, g_c)
        policy_l = (ws * g_c ** (-sigma) / psi) ** (1.0 / eta)
        policy_k = np.clip(
            (1.0 + r) * a_grid[None, :] + ws * policy_l - g_c, 0.0, a_grid[-1]
        )
        dist = np.max(np.abs(g_c - C))
        C = g_c
        if dist < tol:
            break
    return C, policy_k, policy_l, it


def simulate_numpy(policy_k, policy_c, policy_l, a_grid, s, P, r, w, delta, rng,
                   periods, n_agents=1):
    """Panel simulation with linear interpolation (Aiyagari_VFI.m:94-129)."""
    N, na = policy_k.shape
    cumP = np.cumsum(P, axis=1)
    z = rng.integers(0, N, n_agents)
    k = a_grid[rng.integers(0, na, n_agents)]
    out_k = np.empty((periods, n_agents))
    out_c = np.empty((periods, n_agents))
    out_y = np.empty((periods, n_agents))
    out_gy = np.empty((periods, n_agents))
    out_s = np.empty((periods, n_agents))
    for t in range(periods):
        u = rng.random(n_agents)
        z = (cumP[z] < u[:, None]).sum(axis=1)
        k_new = np.array([np.interp(k[i], a_grid, policy_k[z[i]]) for i in range(n_agents)])
        c_new = np.array([np.interp(k[i], a_grid, policy_c[z[i]]) for i in range(n_agents)])
        l_new = np.array([np.interp(k[i], a_grid, policy_l[z[i]]) for i in range(n_agents)])
        y = r * k_new + w * s[z] * l_new
        out_k[t], out_c[t], out_y[t] = k_new, c_new, y
        out_gy[t] = y + delta * k_new
        out_s[t] = out_gy[t] - c_new
        k = k_new
    return out_k, out_c, out_y, out_gy, out_s


@dataclasses.dataclass
class NumpyEquilibriumResult:
    r: float
    w: float
    capital: float
    policy_k: np.ndarray
    policy_c: np.ndarray
    policy_l: np.ndarray
    sim_k: np.ndarray
    r_history: list
    k_supply: list
    k_demand: list
    converged: bool
    solve_seconds: float


def solve_equilibrium_numpy(cfg: AiyagariConfig, *, solver: SolverConfig = SolverConfig(),
                            sim: SimConfig = SimConfig(), eq: EquilibriumConfig = EquilibriumConfig()):
    """GE bisection, NumPy backend (mirrors equilibrium.bisection.solve_equilibrium)."""
    t0 = time.perf_counter()
    prefs, tech = cfg.preferences, cfg.technology
    a_grid, s, P, pi, labor_raw, amin = aiyagari_arrays_numpy(cfg)
    rng = np.random.default_rng(sim.seed)
    N, na = len(s), len(a_grid)

    kwargs = dict(sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol, max_iter=solver.max_iter)
    labor_grid = np.linspace(*cfg.labor_grid_bounds, cfg.labor_grid_n)

    def household(r, warm):
        w = wage_from_r(r, tech.alpha, tech.delta)
        if solver.method == "vfi":
            v0 = warm if warm is not None else np.zeros((N, na))
            if cfg.endogenous_labor:
                v, _, pk, pc, pl, _ = vfi_labor_numpy(v0, a_grid, labor_grid, s, P, r, w,
                                                      psi=prefs.psi, eta=prefs.eta, **kwargs)
            else:
                v, _, pk, pc, pl, _ = vfi_numpy(v0, a_grid, s, P, r, w, **kwargs)
            return v, pk, pc, pl
        C0 = warm if warm is not None else np.tile((1.0 + r) * a_grid + w * s.mean(), (N, 1))
        if cfg.endogenous_labor:
            C, pk, pl, _ = egm_labor_numpy(C0, a_grid, s, P, r, w, amin,
                                           psi=prefs.psi, eta=prefs.eta, **kwargs)
        else:
            C, pk, pl, _ = egm_numpy(C0, a_grid, s, P, r, w, amin, **kwargs)
        return C, pk, C, pl

    warm, *_ = household(eq.r_init, None)
    r_low = eq.r_low
    r_high = eq.r_high if eq.r_high is not None else 1.0 / prefs.beta - 1.0
    r_hist, ks_hist, kd_hist = [], [], []
    converged = False
    r_mid = eq.r_init
    for _ in range(eq.max_iter):
        r_mid = 0.5 * (r_low + r_high)
        w = wage_from_r(r_mid, tech.alpha, tech.delta)
        warm, pk, pc, pl = household(r_mid, warm)
        sim_k, sim_c, *_ = simulate_numpy(pk, pc, pl, a_grid, s, P, r_mid, w,
                                          tech.delta, rng, sim.periods, sim.n_agents)
        supply = sim_k[sim.discard:].mean()
        demand = capital_demand(r_mid, labor_raw, tech.alpha, tech.delta)
        r_hist.append(r_mid)
        ks_hist.append(supply)
        kd_hist.append(demand)
        if abs(supply - demand) < eq.tol:
            converged = True
            break
        if supply > demand:
            r_high = r_mid
        else:
            r_low = r_mid
    w = wage_from_r(r_mid, tech.alpha, tech.delta)
    return NumpyEquilibriumResult(
        r=float(r_mid), w=float(w), capital=float(ks_hist[-1]),
        policy_k=pk, policy_c=pc, policy_l=pl, sim_k=sim_k,
        r_history=r_hist, k_supply=ks_hist, k_demand=kd_hist,
        converged=converged, solve_seconds=time.perf_counter() - t0,
    )
