"""The shared ulp-noise stopping floor of the household solvers.

One implementation of tol_effective = max(tol, noise_floor_ulp * eps *
max|iterate|) so the EGM solvers (single-device and ring-sharded) and the
continuous VFI cannot drift apart in convergence semantics — each
docstring claims "exactly the EGM rule", and this makes the claim
structural (round-4 review finding). Rationale for the rule itself:
solvers/egm.solve_aiyagari_egm's noise_floor_ulp docstring.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["effective_tolerance"]


def effective_tolerance(tol_c, max_abs, *, noise_floor_ulp: float,
                        relative_tol: bool, dtype):
    """Effective stopping tolerance given the iterate's sup-norm `max_abs`
    (callers pass jnp.max(jnp.abs(x)) — or its pmax under shard_map, so the
    sharded routes apply the GLOBAL floor). Static no-op (returns tol_c
    unchanged) when the floor is disabled or the criterion is relative —
    the relative criterion is already scale-free, so the band argument
    does not apply."""
    if noise_floor_ulp <= 0.0 or relative_tol:
        return tol_c
    floor_k = float(noise_floor_ulp) * float(jnp.finfo(dtype).eps)
    return jnp.maximum(tol_c, floor_k * max_abs)
