"""Distributed EGM solver: the full consumption-policy fixed point under one
`jax.shard_map` program with the grid axis sharded across the mesh and the
endogenous-grid knot array RESIDENT per device.

This composes the ring-redistribution inversion (parallel/ring.py) with the
EGM sweep (ops/egm.egm_step, the operator of Aiyagari_EGM.m:74-110) into
the blueprint's actual multi-chip capability (SURVEY.md §2.4(1)): each
device holds a [N, na/D] shard of the consumption iterate and its slice of
the asset grid, and a sweep costs only

  * the tiny [N,N]x[N, na/D] Euler matmul and the elementwise inversion
    arithmetic, all local;
  * one ring rotation of the knot shards (D-1 `lax.ppermute` rounds, ICI
    neighbor traffic) assembling each device's O(na/D) bracket slab — the
    one-hop halo variant (parallel/halo.py) cannot serve this op: the
    endogenous grid's bracket lag is a constant fraction of the grid
    (measured 0.33·na), beyond any legal halo (ring.py module docstring);
  * O(D)-sized collectives: the psum'd bracket starts, an all_gather of
    per-shard cummax tails (the cross-device prefix of the monotonicity
    repair), an all_gather of per-shard head pairs (the below-range
    extrapolation slope), and the pmax'd sup-norm/escape reductions.

No device ever MATERIALIZES more than capacity·na/D knots (+ one window) — the
memory-scaling property GSPMD cannot deliver for this op (its
data-dependent slab gathers force the full knot row to be re-gathered per
device; measured and pinned in tests/test_sim_sharding.TestGridSharding).
tests/test_egm_sharded.py asserts both trajectory equality with the
single-device solver and, on the compiled HLO, that no collective carries
a full-grid-sized operand.

The while_loop runs INSIDE shard_map: the convergence distance is pmax'd
so every device sees the identical replicated carry and the devices
iterate in lockstep — one program launch per solve, not one per sweep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from aiyagari_tpu.parallel.mesh import PartitionSpec as P, shard_map as _shard_map

from aiyagari_tpu.diagnostics.faults import force_escape_point, poison_iterate
from aiyagari_tpu.diagnostics.sentinel import (
    sentinel_cond,
    sentinel_from_leaves,
    sentinel_init,
    sentinel_leaves,
    sentinel_stage_reset,
    sentinel_update,
)
from aiyagari_tpu.diagnostics.telemetry import (
    telemetry_from_leaves,
    telemetry_init,
    telemetry_leaves,
    telemetry_record,
    telemetry_set_trips,
)
from aiyagari_tpu.ops.accel import accel_init, accel_step, project_floor
from aiyagari_tpu.ops.bellman import expectation
from aiyagari_tpu.ops.egm import constrained_consumption_labor
from aiyagari_tpu.ops.precision import matmul_precision_of, plan_stages
from aiyagari_tpu.parallel.halo import cached_program, mesh_fingerprint
from aiyagari_tpu.parallel.ring import (
    DEFAULT_CAPACITY,
    ring_interp_local,
    ring_inverse_local,
    ring_slab_fits,
)
from aiyagari_tpu.solvers._stopping import effective_tolerance
from aiyagari_tpu.solvers.egm import EGMSolution, _cached_grid_bounds, _fetch_scalars
from aiyagari_tpu.utils.utility import (
    crra_marginal,
    crra_marginal_inverse,
    labor_foc_inverse,
)

__all__ = ["solve_aiyagari_egm_sharded", "solve_aiyagari_egm_labor_sharded",
           "solve_aiyagari_egm_sweep_2d"]

_EGM_PROGRAMS: dict = {}


def solve_aiyagari_egm_sharded(mesh, C_init, a_grid, s, P_mat, r, w, amin, *,
                               sigma: float, beta: float, tol: float,
                               max_iter: int, grid_power: float,
                               relative_tol: bool = False,
                               noise_floor_ulp: float = 0.0,
                               capacity: float = DEFAULT_CAPACITY,
                               pad: int = 8,
                               axis: str = "grid",
                               accel=None, ladder=None,
                               telemetry=None, sentinel=None,
                               faults=None) -> EGMSolution:
    """solve_aiyagari_egm with the grid axis sharded over mesh[axis] and the
    knots resident per device (module docstring).

    sentinel carries the failure sentinel (diagnostics/sentinel.py) through
    the sharded while_loop: the watched residual is the pmax'd GLOBAL
    sup-norm and the escape flag is pmax'd too, so every device computes
    the identical verdict and the lockstep loop early-exits on all devices
    at the same sweep; the state crosses the shard_map boundary as
    replicated leaves like the telemetry recorder. faults compiles in the
    deterministic injection points (diagnostics/faults.py). Both None by
    default — compiled out, program unchanged.

    telemetry (a TelemetryConfig) carries the device-resident flight
    recorder through the sharded while_loop (diagnostics/telemetry.py).
    The recorded residual is the pmax'd GLOBAL sup-norm, so every device's
    recorder holds identical buffers; they cross the shard_map boundary as
    replicated outputs and come back as EGMSolution.telemetry. None
    compiles the recorder out (the program is the pre-telemetry one).

    accel opts into safeguarded fixed-point acceleration exactly as in the
    single-device solver; the acceleration's least-squares inner products
    psum over mesh[axis] and its safeguard sup-norms pmax, so every device
    computes the identical extrapolation coefficients and the accelerated
    sharded trajectory matches the single-device accelerated one up to the
    same matmul-reassociation bound as the plain route.

    ladder opts into the mixed-precision solve ladder exactly as in the
    single-device solver (solvers/egm.solve_aiyagari_egm docstring): the
    hot stages run INSIDE the same shard_map program with every carry,
    ring slab, and collective at the hot dtype (halving the per-sweep ICI
    neighbor traffic too), the stopping sup-norms stay pmax'd so all
    devices switch dtype in lockstep at the identical residual, and the
    acceleration history restarts at the cast boundary on every device.

    Same stopping rule, escape contract, and trajectory as the single-device
    windowed fast path (solvers/egm.solve_aiyagari_egm with grid_power>0):
    the bracket counts and knot selections are exact integer/select
    arithmetic and the max-reductions are associative, so the only
    divergence from the unsharded solve is the Euler matmul's reassociation
    under the shard shape — measured <= 2e-14 absolute per sweep in f64
    (pinned at 1e-12 by tests/test_egm_sharded.py). a_grid must be power-spaced with
    exponent `grid_power` (utils/grids.power_grid). Host-level entry — not
    callable inside jit (the mesh/program cache is host state).

    capacity sizes the per-device knot slab (parallel/ring.ring_buffer_size;
    the measured EGM slab requirement is 1.11 shards — default 2.0 is ~80%
    headroom). On escape (bracket beyond the
    slab, or knot density beyond the windowed route's 6x envelope) the
    solution is NaN-poisoned with `escaped=True`; callers fall back exactly
    as for the single-device windowed route (solve_aiyagari_egm_safe's
    contract) — the generic route has no sharded variant, so the fallback
    is the unsharded solver.
    """
    if grid_power <= 0.0:
        raise ValueError(
            "solve_aiyagari_egm_sharded requires a power-spaced grid: pass "
            f"its actual spacing exponent as grid_power, got {grid_power}")
    D = int(mesh.shape[axis])
    N, na = C_init.shape
    if na % D:
        raise ValueError(f"mesh axis size {D} must divide the grid {na}")
    if pad < 1:
        raise ValueError(f"pad must be >= 1, got {pad}")  # ring.py rationale
    if not ring_slab_fits(na, D, capacity):
        # A grid this small has nothing to gain from distribution — fail
        # loudly (ring_slab_fits docstring).
        raise ValueError(
            f"grid of {na} points is too small for the ring slab at "
            f"capacity={capacity} on {D} devices (the slab would exceed "
            "the knot row); use the single-device solver")
    dtype = C_init.dtype
    lo, hi = _cached_grid_bounds(a_grid)
    run = _egm_program(mesh, axis, N, na, lo, hi, float(grid_power),
                       float(capacity), int(pad), float(sigma), float(beta),
                       float(tol), int(max_iter), bool(relative_tol),
                       float(noise_floor_ulp), jnp.dtype(dtype).name, accel,
                       ladder, telemetry, sentinel, faults)
    C, policy_k, dist, it, esc, tol_eff, hot_it, sw_dist, *extra = run(
        C_init, a_grid, s, P_mat,
        jnp.asarray(r, dtype), jnp.asarray(w, dtype), jnp.asarray(amin, dtype),
    )
    n_tele = len(telemetry_leaves(telemetry_init(telemetry)))
    return _fetch_scalars(
        EGMSolution(C, policy_k, jnp.ones_like(C), it, dist, esc, tol_eff,
                    hot_it, sw_dist,
                    telemetry=telemetry_from_leaves(extra[:n_tele]),
                    sentinel=sentinel_from_leaves(extra[n_tele:])))


def _make_egm_local(axis: str, D: int, N: int, na: int, lo: float, hi: float,
                    power: float, capacity: float, pad: int, sigma: float,
                    beta: float, tol: float, max_iter: int,
                    relative_tol: bool, noise_floor_ulp: float,
                    dtype_name: str, accel=None, ladder=None, telemetry=None,
                    sentinel=None, faults=None, lane_sync_axis=None):
    """The ONE-SCENARIO shard-local EGM fixed point over the `axis` grid
    sub-axis — the body both sharded programs wrap: the 1-D grid-sharded
    solve runs shard_map(local) directly (_egm_program), the 2-D
    (scenarios x grid) sweep runs shard_map(vmap(local)) with the lane
    axis mapped over the local scenario shard (_egm_sweep_2d_program).
    Every collective inside names `axis` explicitly, so on a 2-D mesh the
    pmax'd sup-norm / escape / sentinel reductions cover exactly the grid
    SUB-axis — verdicts stay per-lane, never blurred across scenarios.

    lane_sync_axis (the 2-D program only) makes the while_loop TRIP COUNT
    global across that mesh axis while keeping per-lane semantics exact:
    the cond pmax's the lane's continue predicate over the scenario axis
    (so every device executes the identical number of loop iterations —
    the grid-axis collectives inside the body are rendezvous points, and
    scenario groups running DIFFERENT trip counts deadlock them), and the
    body freezes a finished lane's whole carry with its OWN predicate (so
    a converged or sentinel-tripped lane's state is bitwise the state it
    stopped at, exactly as the vmapped-while batching rule would freeze
    it — a frozen lane's sweeps still execute, masked, the quarantine
    wasted-compute contract). None (the 1-D program) leaves cond and body
    untouched — the historical jaxpr, bit-identical."""
    na_loc = na // D
    span = hi - lo
    proj = project_floor()
    stages = plan_stages(ladder, jnp.dtype(dtype_name), noise_floor_ulp)

    def local(C0, a_loc, s, Pm, r, w, amin):
        dev = jax.lax.axis_index(axis)
        # This device's slice of the analytic query grid — the same
        # expression as _finish_inverse's g_of, so the sharded and
        # unsharded routes interpolate onto bitwise-identical queries.
        j = dev * na_loc + jnp.arange(na_loc)

        def run_stage(spec, C_in, pk_in, it0, esc0, tele_in, sent_in):
            dt = jnp.dtype(spec.dtype)
            prec = matmul_precision_of(spec.matmul_precision)
            a_l, s_d, P_d = a_loc.astype(dt), s.astype(dt), Pm.astype(dt)
            r_d, w_d, am_d = r.astype(dt), w.astype(dt), amin.astype(dt)
            q = lo + span * (j.astype(dt) / (na - 1)) ** power
            tol_c = jnp.asarray(tol, dt)
            neg = jnp.array(-jnp.inf, dt)

            def sweep(C):
                # ops/egm.egm_step steps 1-6 on the local shard; see its
                # docstring for the operator and the cummax/clip rationale.
                RHS = (1.0 + r_d) * expectation(
                    P_d, crra_marginal(C, sigma), beta, precision=prec)
                c_next = crra_marginal_inverse(RHS, sigma)
                a_hat = (c_next + a_l[None, :] - w_d * s_d[:, None]) / (1.0 + r_d)
                # Global cummax = local cummax + cross-device prefix of the
                # shard tails (max is associative: bitwise-equal to the
                # unsharded lax.cummax over the full row).
                a_hat = jax.lax.cummax(a_hat, axis=1)
                tails = jax.lax.all_gather(a_hat[:, -1], axis)       # [D, N]
                mask = (jnp.arange(D) < dev)[:, None]
                pref = jnp.max(jnp.where(mask, tails, neg), axis=0)  # [N]
                a_hat = jnp.maximum(a_hat, pref[:, None])
                out, esc = ring_inverse_local(
                    a_hat, q, axis=axis, D=D, n_k=na, n_q=na,
                    lo=lo, hi=hi, power=power, capacity=capacity, pad=pad,
                )
                policy_k = jnp.clip(out, am_d, hi)
                C_new = (1.0 + r_d) * a_l[None, :] + w_d * s_d[:, None] - policy_k
                return C_new, policy_k, esc

            def lane_cond(carry):
                _, _, _, dist, it, _, tol_eff, _, _, sent = carry
                return sentinel_cond(
                    sent, (dist >= tol_eff) & (it < max_iter))

            if lane_sync_axis is None:
                cond = lane_cond
            else:
                def cond(carry):
                    # Global trip count (docstring): any lane anywhere
                    # still running keeps EVERY device iterating, so the
                    # body's grid-axis collectives always rendezvous.
                    return jax.lax.pmax(
                        lane_cond(carry).astype(jnp.int32),
                        lane_sync_axis) > 0

            def body(carry):
                C, _, _, _, it, esc, _, ast, tele, sent = carry
                C_new, policy_k, esc_new = sweep(C)
                C_new = poison_iterate(faults, C_new, it)
                C_new, esc_new = force_escape_point(faults, C_new,
                                                    esc_new)
                diff = jnp.abs(C_new - C)
                # Same criterion family as solve_aiyagari_egm: relative
                # sup-norm when asked, else absolute (+ optional floor).
                loc = (jnp.max(diff / (jnp.abs(C) + 1e-10))
                       if relative_tol else jnp.max(diff))
                dist = jax.lax.pmax(loc, axis)
                # Sup-norm pmax'd so the effective tolerance is global —
                # under a ladder every device therefore switches dtype
                # at the same sweep.
                tol_eff = effective_tolerance(
                    tol_c, jax.lax.pmax(jnp.max(jnp.abs(C_new)), axis),
                    noise_floor_ulp=spec.noise_floor_ulp,
                    relative_tol=relative_tol, dtype=dt)
                # The recorder sees the GLOBAL pmax'd residual, so every
                # device's buffers stay bitwise identical (replicated).
                tele = telemetry_record(tele, dist)
                if sentinel is not None:
                    # The escape flag is LOCAL per device; pmax it so
                    # every device's sentinel verdict is identical and
                    # the lockstep loop exits on all devices together.
                    esc_g = jax.lax.pmax(
                        (esc | (esc_new > 0)).astype(jnp.int32),
                        axis) > 0
                    sent = sentinel_update(sent, dist, config=sentinel,
                                           escaped=esc_g)
                if accel is None:
                    C_next = C_new
                else:
                    # Global extrapolation on local shards: inner products
                    # psum, safeguard norms pmax (accel_step's axis hook).
                    C_next, ast = accel_step(ast, C, C_new, accel=accel,
                                             axis=axis, project=proj)
                    if trip0 is not None:
                        tele = telemetry_set_trips(tele, trip0 + ast.trips)
                return (C_next, C_new, policy_k, dist, it + 1,
                        esc | (esc_new > 0), tol_eff, ast, tele, sent)

            if lane_sync_axis is not None:
                plain_body = body

                def body(carry):  # noqa: F811 — the lane-masked wrapper
                    # Per-lane freeze (docstring): a finished lane's carry
                    # is pinned with ITS OWN predicate while the globally
                    # synced loop keeps iterating for the others.
                    act = lane_cond(carry)
                    new = plain_body(carry)
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(act, n, o), new, carry)

            # Fresh acceleration history per stage: a stale hot-dtype
            # residual history would poison the polish's normal
            # equations (ops/accel.py restart semantics).
            Cd = C_in.astype(dt)
            ast0 = accel_init(Cd, accel) if accel is not None else None
            trip0 = (tele_in.accel_trips
                     if (tele_in is not None and accel is not None)
                     else None)
            # Per-stage sentinel reference restart (the accel-history
            # lesson; sentinel_stage_reset docstring).
            sent_in = sentinel_stage_reset(sent_in)
            init = (Cd, Cd, pk_in.astype(dt), jnp.array(jnp.inf, dt),
                    it0, esc0, tol_c, ast0, tele_in, sent_in)
            out = jax.lax.while_loop(cond, body, init)
            return (out[1], out[2], out[3], out[4], out[5], out[6],
                    out[8], out[9])

        C, pk = C0, jnp.zeros_like(C0)
        it, esc = jnp.int32(0), jnp.array(False)
        hot_it = jnp.int32(0)
        sw = jnp.array(0.0, jnp.dtype(stages[-1].dtype))
        tele = telemetry_init(telemetry)
        sent = sentinel_init(sentinel)
        dist = tol_eff = None
        for spec in stages:
            C, pk, dist, it, esc, tol_eff, tele, sent = run_stage(
                spec, C, pk, it, esc, tele, sent)
            if not spec.is_final:
                hot_it = it
                sw = dist.astype(sw.dtype)
        return (C, pk, dist, it, esc, tol_eff, hot_it, sw,
                *telemetry_leaves(tele), *sentinel_leaves(sent))

    return local


def _egm_program(mesh, axis: str, N: int, na: int, lo: float, hi: float,
                 power: float, capacity: float, pad: int, sigma: float,
                 beta: float, tol: float, max_iter: int, relative_tol: bool,
                 noise_floor_ulp: float, dtype_name: str, accel=None,
                 ladder=None, telemetry=None, sentinel=None, faults=None):
    D = int(mesh.shape[axis])
    n_tele = len(telemetry_leaves(telemetry_init(telemetry)))
    n_sent = len(sentinel_leaves(sentinel_init(sentinel)))

    def build():
        local = _make_egm_local(axis, D, N, na, lo, hi, power, capacity,
                                pad, sigma, beta, tol, max_iter,
                                relative_tol, noise_floor_ulp, dtype_name,
                                accel, ladder, telemetry, sentinel, faults)
        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=(P(None, axis), P(axis), P(), P(), P(), P(), P()),
            out_specs=(P(None, axis), P(None, axis), P(), P(), P(), P(),
                       P(), P()) + (P(),) * (n_tele + n_sent),
        ))

    key = mesh_fingerprint(mesh, axis) + (N, na, lo, hi, power, capacity,
                                          pad, sigma, beta, tol, max_iter,
                                          relative_tol, noise_floor_ulp,
                                          dtype_name, accel, ladder, telemetry,
                                          sentinel, faults)
    return cached_program(_EGM_PROGRAMS, key, build)


_EGM_2D_PROGRAMS: dict = {}


def solve_aiyagari_egm_sweep_2d(mesh, C_init, a_grid, s, P_mat, r, w, amin,
                                *, sigma: float, beta: float, tol: float,
                                max_iter: int, grid_power: float,
                                relative_tol: bool = False,
                                noise_floor_ulp: float = 0.0,
                                capacity: float = DEFAULT_CAPACITY,
                                pad: int = 8,
                                scenario_axis: str = "scenarios",
                                axis: str = "grid",
                                accel=None, ladder=None,
                                telemetry=None, sentinel=None,
                                faults=None) -> EGMSolution:
    """S scenario lanes x the ring-sharded grid solve, as ONE program on a
    2-D (scenarios x grid) mesh (parallel/mesh.make_mesh_2d) — the
    pod-scale composition: the lane axis splits over mesh[scenario_axis]
    (hosts, on a pod) while every lane's knot row rides the SAME ring
    programs as the 1-D grid-sharded solver over mesh[axis] (a host's
    chips). The shard-local body is literally _make_egm_local — the 1-D
    program's — vmapped over the local scenario shard, so the per-sweep
    communication pattern is unchanged per lane: ring rotations, tail
    all_gathers, and pmax'd sup-norms over the grid SUB-axis only. Nothing
    crosses the scenario axis at all (lanes are independent economies),
    which is exactly what makes the axis the host/DCN-friendly one.

    C_init is [S, N, na] (scenario-major); r/w/amin are per-lane [S]
    traced operands — the candidate-rate/price axis of a GE sweep round.
    sigma/beta stay compiled static (shared preferences across lanes,
    like the 1-D program). Lanes iterate in lockstep (the vmapped
    while_loop runs until every lane's cond clears, finished lanes frozen
    by the batching rule's select), and the sentinel verdict is PER LANE:
    the residual each lane's sentinel watches is pmax'd over the grid
    sub-axis alone, so one NaN-poisoned lane early-exits itself while its
    neighbors keep sweeping — the quarantine granularity ISSUE 10 defined,
    now on a 2-D mesh (pinned by tests/test_mesh2d.py).

    Returns an EGMSolution whose leaves carry the leading [S] lane axis
    (policies [S, N, na]; iterations/distance/escaped/verdicts [S]) and
    stay on device — no _fetch_scalars batching here; callers index lanes
    or jax.device_get the batch once."""
    if grid_power <= 0.0:
        raise ValueError(
            "solve_aiyagari_egm_sweep_2d requires a power-spaced grid: pass "
            f"its actual spacing exponent as grid_power, got {grid_power}")
    for ax in (scenario_axis, axis):
        if ax not in mesh.shape:
            raise ValueError(
                f"the 2-D sweep needs a mesh carrying both "
                f"{scenario_axis!r} and {axis!r} axes; got "
                f"{tuple(mesh.axis_names)} (parallel/mesh.make_mesh_2d)")
    Ds, Dg = int(mesh.shape[scenario_axis]), int(mesh.shape[axis])
    S, N, na = C_init.shape
    if S % Ds:
        raise ValueError(
            f"scenario count {S} must divide evenly over the {Ds}-wide "
            f"{scenario_axis!r} mesh axis")
    if na % Dg:
        raise ValueError(f"mesh axis size {Dg} must divide the grid {na}")
    if pad < 1:
        raise ValueError(f"pad must be >= 1, got {pad}")  # ring.py rationale
    if not ring_slab_fits(na, Dg, capacity):
        raise ValueError(
            f"grid of {na} points is too small for the ring slab at "
            f"capacity={capacity} on {Dg} devices (the slab would exceed "
            "the knot row); use a wider grid or a smaller 'grid' axis")
    dtype = C_init.dtype
    lo, hi = _cached_grid_bounds(a_grid)
    run = _egm_sweep_2d_program(
        mesh, scenario_axis, axis, N, na, lo, hi, float(grid_power),
        float(capacity), int(pad), float(sigma), float(beta), float(tol),
        int(max_iter), bool(relative_tol), float(noise_floor_ulp),
        jnp.dtype(dtype).name, accel, ladder, telemetry, sentinel, faults)
    C, policy_k, dist, it, esc, tol_eff, hot_it, sw_dist, *extra = run(
        C_init, a_grid, s, P_mat,
        jnp.asarray(r, dtype), jnp.asarray(w, dtype),
        jnp.asarray(amin, dtype),
    )
    n_tele = len(telemetry_leaves(telemetry_init(telemetry)))
    return EGMSolution(C, policy_k, jnp.ones_like(C), it, dist, esc,
                       tol_eff, hot_it, sw_dist,
                       telemetry=telemetry_from_leaves(extra[:n_tele]),
                       sentinel=sentinel_from_leaves(extra[n_tele:]))


def _egm_sweep_2d_program(mesh, saxis: str, axis: str, N: int, na: int,
                          lo: float, hi: float, power: float,
                          capacity: float, pad: int, sigma: float,
                          beta: float, tol: float, max_iter: int,
                          relative_tol: bool, noise_floor_ulp: float,
                          dtype_name: str, accel=None, ladder=None,
                          telemetry=None, sentinel=None, faults=None):
    Dg = int(mesh.shape[axis])
    tele_t = telemetry_leaves(telemetry_init(telemetry))
    sent_t = sentinel_leaves(sentinel_init(sentinel))

    def build():
        local = _make_egm_local(axis, Dg, N, na, lo, hi, power, capacity,
                                pad, sigma, beta, tol, max_iter,
                                relative_tol, noise_floor_ulp, dtype_name,
                                accel, ladder, telemetry, sentinel, faults,
                                lane_sync_axis=saxis)
        # The lane axis: vmap the 1-D shard-local body over this device's
        # scenario shard. The grid-axis collectives inside batch cleanly
        # (ppermute/all_gather/pmax have batching rules); lane_sync_axis
        # makes the loop trip count global across scenario groups (every
        # device reaches every collective) while finished lanes freeze
        # with their own predicate — per-lane sweeps, one launch.
        lanes = jax.vmap(local, in_axes=(0, None, None, None, 0, 0, 0))
        lane_extra = tuple(P(saxis, *([None] * l.ndim))
                           for l in (tele_t + sent_t))
        return jax.jit(_shard_map(
            lanes, mesh=mesh,
            in_specs=(P(saxis, None, axis), P(axis), P(), P(),
                      P(saxis), P(saxis), P(saxis)),
            out_specs=(P(saxis, None, axis), P(saxis, None, axis),
                       P(saxis), P(saxis), P(saxis), P(saxis),
                       P(saxis), P(saxis)) + lane_extra,
        ))

    key = mesh_fingerprint(mesh, axis) + (saxis, int(mesh.shape[saxis]),
                                          N, na, lo, hi, power, capacity,
                                          pad, sigma, beta, tol, max_iter,
                                          relative_tol, noise_floor_ulp,
                                          dtype_name, accel, ladder,
                                          telemetry, sentinel, faults)
    return cached_program(_EGM_2D_PROGRAMS, key, build)


_EGM_LABOR_PROGRAMS: dict = {}


def solve_aiyagari_egm_labor_sharded(mesh, C_init, a_grid, s, P_mat, r, w,
                                     amin, *, sigma: float, beta: float,
                                     psi: float, eta: float, tol: float,
                                     max_iter: int, grid_power: float,
                                     relative_tol: bool = False,
                                     noise_floor_ulp: float = 0.0,
                                     capacity: float = DEFAULT_CAPACITY,
                                     pad: int = 8,
                                     axis: str = "grid",
                                     accel=None, ladder=None,
                                     telemetry=None, sentinel=None,
                                     faults=None) -> EGMSolution:
    """solve_aiyagari_egm_labor with the grid axis sharded over mesh[axis]
    and the endogenous (knot, consumption) pairs resident per device — the
    labor-family form of solve_aiyagari_egm_sharded, generalizing the ring
    machinery from the grid INVERSION to the monotone VALUE interpolation
    (parallel/ring.ring_interp_local; the hot op of
    Aiyagari_Endogenous_Labor_EGM.m:90, SURVEY.md §2.4(1)).

    Per sweep, everything is local except:
      * the ring rotation now carries the stacked (a_hat, c_next) channels
        (2x the inversion's neighbor traffic, still O(na/D) per device);
      * the cross-device cummax prefix covers BOTH arrays (the windowed
        value kernel's bracketing max/min trick needs c_next monotone too,
        cf. ops/egm.egm_step_labor), folded with the constrained-region
        global first knot into ONE stacked all_gather of [3, N] tails;
      * the O(D) bracket-start psum, head-pair all_gather, and pmax'd
        sup-norm/escape reductions, as in the exogenous program.

    The constrained-region static solution (ops/egm.
    constrained_consumption_labor) is elementwise in the asset grid, so
    each device computes its own slice once per solve — loop-invariant, no
    communication. Same stopping rule, escape contract
    (NaN-poisoning + `escaped`; callers fall back to the unsharded labor
    routes), and trajectory as the single-device windowed fast path, up to
    the Euler matmul's shard-shape reassociation (pinned at 1e-12 by
    tests/test_egm_sharded.py::TestShardedLaborEGMSolver)."""
    if grid_power <= 0.0:
        raise ValueError(
            "solve_aiyagari_egm_labor_sharded requires a power-spaced grid: "
            f"pass its actual spacing exponent as grid_power, got {grid_power}")
    D = int(mesh.shape[axis])
    N, na = C_init.shape
    if na % D:
        raise ValueError(f"mesh axis size {D} must divide the grid {na}")
    if pad < 1:
        raise ValueError(f"pad must be >= 1, got {pad}")  # ring.py rationale
    if not ring_slab_fits(na, D, capacity):
        raise ValueError(
            f"grid of {na} points is too small for the ring slab at "
            f"capacity={capacity} on {D} devices (the slab would exceed "
            "the knot row); use the single-device solver")
    dtype = C_init.dtype
    lo, hi = _cached_grid_bounds(a_grid)
    run = _egm_labor_program(mesh, axis, N, na, lo, hi, float(grid_power),
                             float(capacity), int(pad), float(sigma),
                             float(beta), float(psi), float(eta), float(tol),
                             int(max_iter), bool(relative_tol),
                             float(noise_floor_ulp), jnp.dtype(dtype).name,
                             accel, ladder, telemetry, sentinel, faults)
    (C, policy_k, policy_l, dist, it, esc, tol_eff, hot_it, sw_dist,
     *extra) = run(
        C_init, a_grid, s, P_mat,
        jnp.asarray(r, dtype), jnp.asarray(w, dtype), jnp.asarray(amin, dtype),
    )
    n_tele = len(telemetry_leaves(telemetry_init(telemetry)))
    return _fetch_scalars(
        EGMSolution(C, policy_k, policy_l, it, dist, esc, tol_eff,
                    hot_it, sw_dist,
                    telemetry=telemetry_from_leaves(extra[:n_tele]),
                    sentinel=sentinel_from_leaves(extra[n_tele:])))


def _egm_labor_program(mesh, axis: str, N: int, na: int, lo: float, hi: float,
                       power: float, capacity: float, pad: int, sigma: float,
                       beta: float, psi: float, eta: float, tol: float,
                       max_iter: int, relative_tol: bool,
                       noise_floor_ulp: float, dtype_name: str, accel=None,
                       ladder=None, telemetry=None, sentinel=None,
                       faults=None):
    D = int(mesh.shape[axis])
    na_loc = na // D
    span = hi - lo
    proj = project_floor()
    stages = plan_stages(ladder, jnp.dtype(dtype_name), noise_floor_ulp)
    n_tele = len(telemetry_leaves(telemetry_init(telemetry)))
    n_sent = len(sentinel_leaves(sentinel_init(sentinel)))

    def build():
        def local(C0, a_loc, s, Pm, r, w, amin):
            dev = jax.lax.axis_index(axis)
            j = dev * na_loc + jnp.arange(na_loc)

            def run_stage(spec, C_in, pk_in, pl_in, it0, esc0, tele_in,
                          sent_in):
                dt = jnp.dtype(spec.dtype)
                prec = matmul_precision_of(spec.matmul_precision)
                a_l, s_d, P_d = a_loc.astype(dt), s.astype(dt), Pm.astype(dt)
                r_d, w_d, am_d = r.astype(dt), w.astype(dt), amin.astype(dt)
                q = lo + span * (j.astype(dt) / (na - 1)) ** power
                tol_c = jnp.asarray(tol, dt)
                neg = jnp.array(-jnp.inf, dt)
                ws = w_d * s_d[:, None]                               # [N, 1]
                # Loop-invariant constrained-region solution on the local
                # grid slice (elementwise in a_grid — no communication);
                # rebuilt per stage: loop-invariant but dtype-dependent.
                c_con = constrained_consumption_labor(
                    a_l, s_d, r_d, w_d, am_d, sigma=sigma, psi=psi, eta=eta)

                def sweep(C):
                    # ops/egm.egm_step_labor on the local shard; see its
                    # docstring for the operator and the reference quirks kept.
                    RHS = (1.0 + r_d) * expectation(
                        P_d, crra_marginal(C, sigma), beta, precision=prec)
                    c_next = crra_marginal_inverse(RHS, sigma)
                    l_endo = labor_foc_inverse(
                        ws * crra_marginal(c_next, sigma), psi, eta)      # :86
                    a_hat = (c_next + a_l[None, :] - ws * l_endo) / (1.0 + r_d)
                    # Global cummax on BOTH arrays: local cummax + cross-device
                    # prefix of the shard tails (associative, bitwise-equal to
                    # the unsharded row cummax). One stacked all_gather also
                    # carries the global first endogenous knot for the
                    # constrained region (device 0's head is prefix-free).
                    a_hat = jax.lax.cummax(a_hat, axis=1)
                    c_next = jax.lax.cummax(c_next, axis=1)
                    packed = jnp.stack(
                        [a_hat[:, -1], c_next[:, -1], a_hat[:, 0]])   # [3, N]
                    g = jax.lax.all_gather(packed, axis)              # [D, 3, N]
                    mask = (jnp.arange(D) < dev)[:, None]
                    a_hat = jnp.maximum(
                        a_hat, jnp.max(jnp.where(mask, g[:, 0], neg), axis=0)[:, None])
                    c_next = jnp.maximum(
                        c_next, jnp.max(jnp.where(mask, g[:, 1], neg), axis=0)[:, None])
                    first_knot = g[0, 2]                              # [N]
                    g_c, esc = ring_interp_local(
                        a_hat, c_next, q, axis=axis, D=D, n_k=na, n_q=na,
                        lo=lo, hi=hi, power=power, capacity=capacity, pad=pad,
                    )
                    # Constrained region + the reference's sequencing quirks,
                    # exactly as ops/egm.egm_step_labor (its comments) — against
                    # the CALLER's grid shard, as the single-device route
                    # compares a_grid, not the analytic rebuild.
                    g_c = jnp.where(a_l[None, :] < first_knot[:, None], c_con, g_c)
                    g_c = jnp.where(a_l[None, :] < am_d, am_d, g_c)       # :91
                    # The constrained-region overwrite is FINITE, so it would
                    # partially un-poison an escaped sweep — re-poison to keep
                    # the whole-solution NaN contract of the exogenous route.
                    g_c = jnp.where(esc > 0, jnp.nan, g_c)
                    policy_l = labor_foc_inverse(
                        ws * crra_marginal(g_c, sigma), psi, eta)         # :95
                    policy_k = jnp.clip(
                        (1.0 + r_d) * a_l[None, :] + ws * policy_l - g_c,
                        0.0, hi)                                          # :99
                    return g_c, policy_k, policy_l, esc

                def cond(carry):
                    _, _, _, _, dist, it, _, tol_eff, _, _, sent = carry
                    return sentinel_cond(
                        sent, (dist >= tol_eff) & (it < max_iter))

                def body(carry):
                    C, _, _, _, _, it, esc, _, ast, tele, sent = carry
                    C_new, policy_k, policy_l, esc_new = sweep(C)
                    C_new = poison_iterate(faults, C_new, it)
                    C_new, esc_new = force_escape_point(faults, C_new,
                                                        esc_new)
                    diff = jnp.abs(C_new - C)
                    local_d = (jnp.max(diff / (jnp.abs(C) + 1e-10))
                               if relative_tol else jnp.max(diff))
                    dist = jax.lax.pmax(local_d, axis)
                    tol_eff = effective_tolerance(
                        tol_c, jax.lax.pmax(jnp.max(jnp.abs(C_new)), axis),
                        noise_floor_ulp=spec.noise_floor_ulp,
                        relative_tol=relative_tol, dtype=dt)
                    # Global pmax'd residual: replicated recorder buffers.
                    tele = telemetry_record(tele, dist)
                    if sentinel is not None:
                        # Escape pmax'd so every device's verdict agrees
                        # (the exogenous program's rationale).
                        esc_g = jax.lax.pmax(
                            (esc | (esc_new > 0)).astype(jnp.int32),
                            axis) > 0
                        sent = sentinel_update(sent, dist, config=sentinel,
                                               escaped=esc_g)
                    if accel is None:
                        C_next = C_new
                    else:
                        C_next, ast = accel_step(ast, C, C_new, accel=accel,
                                                 axis=axis, project=proj)
                        if trip0 is not None:
                            tele = telemetry_set_trips(tele, trip0 + ast.trips)
                    return (C_next, C_new, policy_k, policy_l, dist, it + 1,
                            esc | (esc_new > 0), tol_eff, ast, tele, sent)

                Cd = C_in.astype(dt)
                ast0 = accel_init(Cd, accel) if accel is not None else None
                trip0 = (tele_in.accel_trips
                         if (tele_in is not None and accel is not None)
                         else None)
                # Per-stage sentinel reference restart (exogenous-program
                # rationale).
                sent_in = sentinel_stage_reset(sent_in)
                init = (Cd, Cd, pk_in.astype(dt), pl_in.astype(dt),
                        jnp.array(jnp.inf, dt), it0, esc0, tol_c, ast0,
                        tele_in, sent_in)
                out = jax.lax.while_loop(cond, body, init)
                return (out[1], out[2], out[3], out[4], out[5], out[6],
                        out[7], out[9], out[10])

            z = jnp.zeros_like(C0)
            C, pk, pl = C0, z, z
            it, esc = jnp.int32(0), jnp.array(False)
            hot_it = jnp.int32(0)
            sw = jnp.array(0.0, jnp.dtype(stages[-1].dtype))
            tele = telemetry_init(telemetry)
            sent = sentinel_init(sentinel)
            dist = tol_eff = None
            for spec in stages:
                C, pk, pl, dist, it, esc, tol_eff, tele, sent = run_stage(
                    spec, C, pk, pl, it, esc, tele, sent)
                if not spec.is_final:
                    hot_it = it
                    sw = dist.astype(sw.dtype)
            return (C, pk, pl, dist, it, esc, tol_eff, hot_it, sw,
                    *telemetry_leaves(tele), *sentinel_leaves(sent))

        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=(P(None, axis), P(axis), P(), P(), P(), P(), P()),
            out_specs=(P(None, axis), P(None, axis), P(None, axis),
                       P(), P(), P(), P(), P(), P())
            + (P(),) * (n_tele + n_sent),
        ))

    key = mesh_fingerprint(mesh, axis) + (N, na, lo, hi, power, capacity,
                                          pad, sigma, beta, psi, eta, tol,
                                          max_iter, relative_tol,
                                          noise_floor_ulp, dtype_name, accel,
                                          ladder, telemetry, sentinel, faults)
    return cached_program(_EGM_LABOR_PROGRAMS, key, build)
