"""Non-stochastic (Young 2010) cross-section dynamics for Krusell-Smith: push
the histogram over (employment, capital) gridpoints through the policy along
the aggregate-shock path, instead of simulating 10,000 discrete households.

The reference's panel simulator (Krusell_Smith_VFI.m:70-94,222-248) carries
Monte-Carlo sampling error into the ALM regression — the regression chases
noise, which is why damping 0.3 is needed. The histogram form is exact given
the grid: per period the whole cross-section moves with one policy lookup
(the distribution lives ON k_grid, so the policy needs no interpolation in
k at all), a two-point lottery scatter, and a 2x2 employment mixing whose
conditional matrices (eps_trans) by construction reproduce u(z) each period
exactly. Deterministic, RNG-free, and O(nk) per period instead of
O(population). The per-period lottery push runs on the scatter-free
DistributionBackend layer (ops/pushforward.py) like every other
cross-section path; `pushforward` selects the route.

The reference has no analogue; this closure is selected with
solve(..., aggregation="distribution") / solve_krusell_smith(closure=
"histogram").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.models.krusell_smith import state_index
from aiyagari_tpu.ops.pushforward import lottery_scatter, pushforward_step
from aiyagari_tpu.sim.distribution import young_lottery

__all__ = ["initial_distribution", "distribution_capital_path"]


def initial_distribution(k_grid, K_grid, u0, dtype):
    """Histogram matching the panel simulator's start: everyone at
    k = K_grid[0] (snapped onto k_grid by the lottery), unemployed with
    probability u0.

    The point mass deposits through the SHARED lottery helper
    (young_lottery + ops/pushforward.lottery_scatter) rather than bespoke
    scalar `.at[]` scatters, so it inherits the same edge-clipping contract
    as every other lottery entry: a start point at (or beyond) the top of
    k_grid collapses onto the last knot with total mass exactly 1 instead
    of writing out of bounds (tests/test_pushforward.py pins the edge)."""
    point = jnp.full((1, 1), K_grid[0], dtype)
    idx, w_lo = young_lottery(point, k_grid)
    k_mass = lottery_scatter(jnp.ones((1, 1), dtype), idx, w_lo,
                             k_grid.shape[0])[0]
    return jnp.stack([(1.0 - u0) * k_mass, u0 * k_mass])   # [2, nk], eps 0=employed


@partial(jax.jit, static_argnames=("T", "pushforward"))
def distribution_capital_path(k_opt, k_grid, K_grid, z_path, eps_trans, mu_init, *,
                              T: int, pushforward: str = "auto"):
    """Deterministic aggregate-capital path under policy k_opt [ns, nK, nk].

    mu_init [2, nk]: mass over (eps, k) with eps 0=employed (the ks_panel
    convention); rows sum to the employment shares. Per step t:

      1. policy at the scalar K_t by linear interpolation in K (the same
         edge-extrapolating rule as simulate_capital_path);
      2. since mu lives on k_grid, next capital for each (eps, gridpoint) is
         just the policy row at the joint state (z_t, eps) — K_{t+1} =
         <mu, k'> exactly;
      3. Young two-point lottery scatters each row's mass onto k_grid;
      4. employment mixing with the 2x2 conditional chain selected by
         (z_t -> z_{t+1}) (eps_trans, as in simulate_employment_panel).

    Returns (K_ts [T], mu_final [2, nk]).
    """
    nK = K_grid.shape[0]
    n_eps = mu_init.shape[0]

    def step(carry, inp):
        mu, K_t = carry
        z_t, z_next = inp
        iK = jnp.clip(jnp.searchsorted(K_grid, K_t, side="right") - 1, 0, nK - 2)
        tK = (K_t - K_grid[iK]) / (K_grid[iK + 1] - K_grid[iK])
        pol_at_K = k_opt[:, iK, :] * (1.0 - tK) + k_opt[:, iK + 1, :] * tK   # [ns, nk]
        # eps row order 0=employed, 1=unemployed -> employed flag 1-eps.
        s_rows = state_index(z_t, 1 - jnp.arange(n_eps))                     # [2]
        kp = pol_at_K[s_rows]                                                # [2, nk]
        K_next = jnp.sum(mu * kp)
        idx, w_lo = young_lottery(kp, k_grid)
        # Same lottery push-forward + chain-mixing kernel as the Aiyagari
        # stationary iteration (ops/pushforward.py; `pushforward` selects
        # the backend, scatter-free by default), with the (z_t -> z_{t+1})
        # conditional employment chain in the role of P.
        mu_next = pushforward_step(mu, idx, w_lo, eps_trans[z_t, z_next],
                                   backend=pushforward)
        return (mu_next, K_next), K_t

    # NOT unrolled: the agent panel's scan gains +8% from unroll=8
    # (sim/ks_panel._panel_scan), but this lottery-push body (scatter-heavy
    # before the ops/pushforward rewrite) measured only ~2% (148.8 -> 146.1
    # ms at reference scale, within noise) — not worth the 8x body compile.
    (mu, K_last), K_head = jax.lax.scan(
        step, (mu_init, jnp.sum(mu_init * k_grid[None, :])),
        (z_path[:-1], z_path[1:]),
    )
    K_ts = jnp.concatenate([K_head, K_last[None]])
    return K_ts, mu
