"""Krusell-Smith shock processes and the cross-sectional agent-panel
simulator — the framework's flagship data-parallel workload.

The reference generates the T x 10,000 idiosyncratic shock panel with a scalar
double loop (Krusell_Smith_VFI.m:70-94) and steps the panel by grouping agents
per state and calling 2-D interpolants (:222-248). Here both are lax.scans over
time carrying the whole cross-section as a vector: per-step work is a batched
gather/interpolation over agents, so the agent axis shards across TPU devices
(jax.sharding) and the per-step aggregate K_{t+1} = mean(k) becomes a
cross-device reduction that XLA lowers onto ICI.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.models.krusell_smith import state_index
from aiyagari_tpu.parallel.mesh import shard_map as _shard_map
from aiyagari_tpu.ops.interp import state_policy_interp, state_policy_interp_power

__all__ = [
    "simulate_aggregate_shocks",
    "simulate_employment_panel",
    "simulate_capital_path",
    "simulate_capital_paths_batch",
    "simulate_capital_path_shardmap",
]


@partial(jax.jit, static_argnames=("T",))
def simulate_aggregate_shocks(pz, key, *, T: int):
    """Two-state aggregate z path (0=good, 1=bad), started in the good state
    (Krusell_Smith_VFI.m:58-68). Returns int32 [T]."""

    def step(z, key_t):
        u = jax.random.uniform(key_t, dtype=pz.dtype)
        stay = pz[z, z]
        z_new = jnp.where(u > stay, 1 - z, z)
        return z_new, z_new

    keys = jax.random.split(key, T - 1)
    _, tail = jax.lax.scan(step, jnp.int32(0), keys)
    return jnp.concatenate([jnp.zeros((1,), jnp.int32), tail])


@partial(jax.jit, static_argnames=("T", "population"))
def simulate_employment_panel(z_path, eps_trans, u_good, u_bad, key, *, T: int, population: int):
    """Employment panel [T, population] (0=employed, 1=unemployed), each agent
    following the conditional chain selected by (z_{t-1} -> z_t)
    (Krusell_Smith_VFI.m:70-94, vectorized over agents).

    Initial cross-section: unemployed with the period-1 state's unemployment
    rate. (The reference's initialization `(rand > ug) + 1` at :71 makes 96%
    of agents *unemployed* under its eps_grid ordering — an initial-condition
    slip that washes out after the discard window; we use the intended rate.)
    """
    k0, k_scan = jax.random.split(key)
    u0 = jnp.where(z_path[0] == 0, u_good, u_bad)
    eps0 = (jax.random.uniform(k0, (population,), dtype=eps_trans.dtype) < u0).astype(jnp.int32)

    def step(carry, inp):
        eps_prev, z_prev = carry
        z_t, key_t = inp
        u = jax.random.uniform(key_t, (population,), dtype=eps_trans.dtype)
        # Stay probability given previous employment status:
        # employed (0): p11 = eps_trans[zp, zt, 0, 0]; unemployed (1): p00 = [.,., 1, 1].
        p_emp = jnp.where(
            eps_prev == 0,
            eps_trans[z_prev, z_t, 0, 0],   # employed -> employed
            eps_trans[z_prev, z_t, 1, 0],   # unemployed -> employed
        )
        eps_new = (u > p_emp).astype(jnp.int32)   # 0 employed iff u <= p_emp (:87-92)
        return (eps_new, z_t), eps_new

    keys = jax.random.split(k_scan, T - 1)
    (_, _), tail = jax.lax.scan(step, (eps0, z_path[0]), (z_path[1:], keys))
    return jnp.concatenate([eps0[None, :], tail], axis=0)


def _panel_scan(k_opt, k_grid, K_grid, z_path, eps_panel, k_population, mean_fn,
                grid_power: float = 0.0):
    """The per-period panel transition shared by both simulator variants
    (mean_fn is jnp.mean for the jit/GSPMD path, a pmean-of-local-mean for the
    explicit shard_map path; the sharding tests assert 1e-12 agreement).

    Per step (Krusell_Smith_VFI.m:222-248): each agent's joint state from
    (z_t, eps_{t,i}); policy evaluated by bilinear interpolation in (k, K) —
    realized as a 1-D linear interpolation in K (scalar weight per step) nested
    with a batched per-agent linear interpolation in k; K_{t+1} = mean(k').

    grid_power > 0 declares k_grid power-spaced with that exponent and takes
    the analytic-bucket route (state_policy_interp_power): same edge-segment
    extrapolation, ~4e-6 agreement at f32, and ~2x per step at 100k+
    agents/device (par at the reference's 10k — see the interp docstring).
    """
    nK = K_grid.shape[0]
    glo, ghi = k_grid[0], k_grid[-1]      # traced scalars; fine under jit

    def step(carry, inp):
        k_pop, K_t = carry
        z_t, eps_t = inp
        s_t = state_index(z_t, 1 - eps_t)                       # [pop] joint state
        # Interpolate the policy table in K at the scalar K_t (linear,
        # extrapolating with edge segments like griddedInterpolant 'linear').
        iK = jnp.clip(jnp.searchsorted(K_grid, K_t, side="right") - 1, 0, nK - 2)
        tK = (K_t - K_grid[iK]) / (K_grid[iK + 1] - K_grid[iK])
        pol_at_K = k_opt[:, iK, :] * (1.0 - tK) + k_opt[:, iK + 1, :] * tK   # [ns, nk]
        # Gather-free policy evaluation: state selection and bucket selection
        # are one-hot contractions (ops/interp.py state_policy_interp) — TPU
        # gathers of agent-indexed rows were the measured bottleneck, and the
        # one-hot form also shards cleanly along the agent axis.
        if grid_power > 0.0:
            k_new = state_policy_interp_power(pol_at_K, s_t, k_pop,
                                              lo=glo, hi=ghi, power=grid_power)
        else:
            k_new = state_policy_interp(k_grid, pol_at_K, s_t, k_pop)
        return (k_new, mean_fn(k_new)), K_t

    # unroll=8: the time axis is sequential (K_t feeds t+1), but unrolling
    # the scan body trims the per-step loop overhead — measured 21.0 ->
    # 19.4 ms/sim (+8%) at the reference panel on the v5e; flat beyond 8.
    (k_population, K_last), K_head = jax.lax.scan(
        step, (k_population, mean_fn(k_population)),
        (z_path[:-1], eps_panel[:-1]), unroll=8,
    )
    K_ts = jnp.concatenate([K_head, K_last[None]])
    return K_ts, k_population


_GRID_POWER_OK: dict = {}


def _check_grid_power(k_grid, grid_power: float) -> None:
    """Host-level probe that k_grid really follows the analytic spacing law
    k[i] = lo + (hi-lo)*(i/(n-1))**grid_power the analytic-bucket route
    assumes (ops/interp.state_policy_interp_power): a caller passing
    grid_power > 0 with any OTHER grid would get silently wrong
    interpolation, since the analytic knots diverge from the stored ones.
    Two interior probe points against the formula catch every wrong-spacing
    case at f32 resolution. Id-keyed memo (the _cached_grid_bounds pattern,
    solvers/egm.py): the ALM loop re-simulates every iteration on the same
    grid array, so validation costs one device fetch per distinct grid,
    not per call. Under a TRACE (callers composing the simulator inside
    their own jit, e.g. the driver's forward step — even a concrete
    closed-over k_grid yields tracers from any op there) the probe skips:
    the precondition is then on that caller's concrete operand."""
    if grid_power <= 0.0 or isinstance(k_grid, jax.core.Tracer):
        return
    key = (id(k_grid), float(grid_power))
    hit = _GRID_POWER_OK.get(key)
    if hit is not None and hit is k_grid:
        return
    import numpy as np

    n = int(k_grid.shape[-1])
    try:
        probes = np.asarray(jax.device_get(
            k_grid[jnp.asarray([0, 1, n // 2, n - 1])]))
    except jax.errors.TracerArrayConversionError:
        return    # inside someone else's jit: nothing concrete to probe
    # `probes` is HOST numpy (one batched device_get above) — these
    # float()s index host memory, not the device.
    lo, hi = float(probes[0]), float(probes[-1])     # noqa: AIYA202
    scale = max(abs(lo), abs(hi), 1.0)
    for j, v in ((1, float(probes[1])), (n // 2, float(probes[2]))):  # noqa: AIYA202
        want = lo + (hi - lo) * (j / (n - 1)) ** grid_power
        if abs(v - want) > 1e-4 * scale:
            raise ValueError(
                f"grid_power={grid_power} declared, but k_grid[{j}]={v:.6g} "
                f"!= the analytic power-grid value {want:.6g} (lo={lo:.6g}, "
                f"hi={hi:.6g}, n={n}): the analytic-bucket interpolation "
                "would silently mis-bucket — pass the grid's true spacing "
                "exponent, or grid_power=0.0 for the generic route")
    if len(_GRID_POWER_OK) >= 16:
        _GRID_POWER_OK.pop(next(iter(_GRID_POWER_OK)))
    _GRID_POWER_OK[key] = k_grid


@partial(jax.jit, static_argnames=("T", "grid_power"))
def _simulate_capital_path_jit(k_opt, k_grid, K_grid, z_path, eps_panel,
                               k_population, *, T: int,
                               grid_power: float = 0.0):
    return _panel_scan(k_opt, k_grid, K_grid, z_path, eps_panel, k_population,
                       jnp.mean, grid_power)


def simulate_capital_path(k_opt, k_grid, K_grid, z_path, eps_panel, k_population, *,
                          T: int, grid_power: float = 0.0):
    """Step the agent panel through T-1 periods under the policy k_opt
    [ns, nK, nk]; returns (K_ts [T], k_population_final).

    The agent axis (k_population, eps_panel columns) may be sharded across
    devices; the mean lowers to a psum over ICI (implicitly, via GSPMD — see
    simulate_capital_path_shardmap for the explicit-collective form).
    grid_power > 0 selects the analytic-bucket interpolation for a
    power-spaced k_grid (_panel_scan docstring); the declared exponent is
    validated against the stored knots once per grid array
    (_check_grid_power) — host-level entry, not callable inside jit.

    k_population is NOT donated: callers legitimately reuse the same initial
    cross-section across runs (e.g. to compare this path against the
    shard_map variant), and donating a [pop]-sized buffer saves nothing
    next to the [T, pop] shock panel.
    """
    _check_grid_power(k_grid, grid_power)
    return _simulate_capital_path_jit(k_opt, k_grid, K_grid, z_path,
                                      eps_panel, k_population, T=T,
                                      grid_power=grid_power)


@partial(jax.jit, static_argnames=("T", "grid_power"))
def _simulate_capital_paths_batch_jit(k_opt, k_grid, K_grid, z_paths,
                                      eps_panels, k_populations, *, T: int,
                                      grid_power: float = 0.0):
    return jax.vmap(
        lambda z, e, k0: _panel_scan(k_opt, k_grid, K_grid, z, e, k0,
                                     jnp.mean, grid_power)
    )(z_paths, eps_panels, k_populations)


def simulate_capital_paths_batch(k_opt, k_grid, K_grid, z_paths, eps_panels,
                                 k_populations, *, T: int,
                                 grid_power: float = 0.0):
    """W independent panel simulations in ONE scan: z_paths [W, T],
    eps_panels [W, T, pop], k_populations [W, pop] ->
    (K_ts [W, T], k_populations_final [W, pop]).

    Why this exists (round 5, VERDICT round 4 weak #7): the single-panel
    scan at the reference's 10k agents is LAUNCH-bound, not
    bandwidth-bound — ~17.6 us/step of which ~1.5 us is the [pop, nk]
    interpolation's compute (membw_frac 0.31; the same step at 100k
    agents reads 0.62). The time axis is sequential through K_t = mean(k)
    and cannot be widened, but INDEPENDENT sims can: vmapping the
    per-step transition makes every kernel in the scan body serve W sims,
    amortizing the fixed per-step overhead across the batch — measured
    4.2x aggregate agent-steps/s at W=8 x 10k agents on the v5e
    (BENCHMARKS.md round 5). Use it wherever sims are embarrassingly
    parallel: seed batteries, bootstrap standard errors, parameter
    sweeps. The per-sim arithmetic is IDENTICAL to simulate_capital_path
    (vmap batches the same kernels; pinned to 1e-12 by
    tests/test_sim_sharding.py::test_batch_matches_single_sims).
    """
    _check_grid_power(k_grid, grid_power)
    return _simulate_capital_paths_batch_jit(
        k_opt, k_grid, K_grid, z_paths, eps_panels, k_populations, T=T,
        grid_power=grid_power)


@lru_cache(maxsize=None)
def _shardmap_panel_fn(mesh, axis: str, grid_power: float = 0.0):
    """Build (and cache per mesh/axis, so repeated calls hit jit's trace
    cache instead of recompiling the scan) the shard_map panel program."""
    from aiyagari_tpu.parallel.mesh import PartitionSpec as P

    def shard_body(k_opt, k_grid, K_grid, z_path, eps_local, k_pop_local):
        def gmean(x):
            return jax.lax.pmean(jnp.mean(x), axis)

        K_ts, k_pop_local = _panel_scan(
            k_opt, k_grid, K_grid, z_path, eps_local, k_pop_local, gmean,
            grid_power
        )
        return K_ts, k_pop_local

    return jax.jit(_shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(None, axis), P(axis)),
        out_specs=(P(), P(axis)),
    ))


def simulate_capital_path_shardmap(mesh, k_opt, k_grid, K_grid, z_path, eps_panel,
                                   k_population, *, axis: str = "agents",
                                   grid_power: float = 0.0):
    """simulate_capital_path with the cross-device collective written
    explicitly: the panel runs under jax.shard_map with each device holding a
    [T, population/n_devices] shard, and the per-step aggregate
    K_{t+1} = mean(k') is a local mean followed by lax.pmean over the mesh
    axis — the literal psum-over-ICI reduction of SURVEY.md §2.4(2), rather
    than the implicit one GSPMD derives for the jit path.

    Semantically identical to simulate_capital_path (the sharding tests assert
    allclose at 1e-12); exists so the collective layer has an explicit,
    inspectable form and so per-device work cannot be resharded by the
    compiler. Requires population % mesh.shape[axis] == 0 (pmean of equal
    local means is only then the global mean).
    """
    n = mesh.shape[axis]
    population = int(k_population.shape[0])
    if population % n != 0:
        raise ValueError(
            f"population {population} not divisible by mesh axis {axis!r} size {n}"
        )
    _check_grid_power(k_grid, grid_power)
    run = _shardmap_panel_fn(mesh, axis, float(grid_power))
    return run(k_opt, k_grid, K_grid, z_path, eps_panel, k_population)
