"""Krusell-Smith shock processes and the cross-sectional agent-panel
simulator — the framework's flagship data-parallel workload.

The reference generates the T x 10,000 idiosyncratic shock panel with a scalar
double loop (Krusell_Smith_VFI.m:70-94) and steps the panel by grouping agents
per state and calling 2-D interpolants (:222-248). Here both are lax.scans over
time carrying the whole cross-section as a vector: per-step work is a batched
gather/interpolation over agents, so the agent axis shards across TPU devices
(jax.sharding) and the per-step aggregate K_{t+1} = mean(k) becomes a
cross-device reduction that XLA lowers onto ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.models.krusell_smith import state_index
from aiyagari_tpu.ops.interp import state_policy_interp

__all__ = ["simulate_aggregate_shocks", "simulate_employment_panel", "simulate_capital_path"]


@partial(jax.jit, static_argnames=("T",))
def simulate_aggregate_shocks(pz, key, *, T: int):
    """Two-state aggregate z path (0=good, 1=bad), started in the good state
    (Krusell_Smith_VFI.m:58-68). Returns int32 [T]."""

    def step(z, key_t):
        u = jax.random.uniform(key_t, dtype=pz.dtype)
        stay = pz[z, z]
        z_new = jnp.where(u > stay, 1 - z, z)
        return z_new, z_new

    keys = jax.random.split(key, T - 1)
    _, tail = jax.lax.scan(step, jnp.int32(0), keys)
    return jnp.concatenate([jnp.zeros((1,), jnp.int32), tail])


@partial(jax.jit, static_argnames=("T", "population"))
def simulate_employment_panel(z_path, eps_trans, u_good, u_bad, key, *, T: int, population: int):
    """Employment panel [T, population] (0=employed, 1=unemployed), each agent
    following the conditional chain selected by (z_{t-1} -> z_t)
    (Krusell_Smith_VFI.m:70-94, vectorized over agents).

    Initial cross-section: unemployed with the period-1 state's unemployment
    rate. (The reference's initialization `(rand > ug) + 1` at :71 makes 96%
    of agents *unemployed* under its eps_grid ordering — an initial-condition
    slip that washes out after the discard window; we use the intended rate.)
    """
    k0, k_scan = jax.random.split(key)
    u0 = jnp.where(z_path[0] == 0, u_good, u_bad)
    eps0 = (jax.random.uniform(k0, (population,), dtype=eps_trans.dtype) < u0).astype(jnp.int32)

    def step(carry, inp):
        eps_prev, z_prev = carry
        z_t, key_t = inp
        u = jax.random.uniform(key_t, (population,), dtype=eps_trans.dtype)
        # Stay probability given previous employment status:
        # employed (0): p11 = eps_trans[zp, zt, 0, 0]; unemployed (1): p00 = [.,., 1, 1].
        p_emp = jnp.where(
            eps_prev == 0,
            eps_trans[z_prev, z_t, 0, 0],   # employed -> employed
            eps_trans[z_prev, z_t, 1, 0],   # unemployed -> employed
        )
        eps_new = (u > p_emp).astype(jnp.int32)   # 0 employed iff u <= p_emp (:87-92)
        return (eps_new, z_t), eps_new

    keys = jax.random.split(k_scan, T - 1)
    (_, _), tail = jax.lax.scan(step, (eps0, z_path[0]), (z_path[1:], keys))
    return jnp.concatenate([eps0[None, :], tail], axis=0)


@partial(jax.jit, static_argnames=("T",), donate_argnames=("k_population",))
def simulate_capital_path(k_opt, k_grid, K_grid, z_path, eps_panel, k_population, *, T: int):
    """Step the agent panel through T-1 periods under the policy k_opt
    [ns, nK, nk]; returns (K_ts [T], k_population_final).

    Per step (Krusell_Smith_VFI.m:222-248): each agent's joint state from
    (z_t, eps_{t,i}); policy evaluated by bilinear interpolation in (k, K) —
    realized as a 1-D linear interpolation in K (scalar weight per step) nested
    with a batched per-agent linear interpolation in k; K_{t+1} = mean(k').
    The agent axis (k_population, eps_panel columns) may be sharded across
    devices; the mean lowers to a psum over ICI.
    """
    nK = K_grid.shape[0]

    def step(carry, inp):
        k_pop, K_t = carry
        z_t, eps_t = inp
        s_t = state_index(z_t, 1 - eps_t)                       # [pop] joint state
        # Interpolate the policy table in K at the scalar K_t (linear,
        # extrapolating with edge segments like griddedInterpolant 'linear').
        iK = jnp.clip(jnp.searchsorted(K_grid, K_t, side="right") - 1, 0, nK - 2)
        tK = (K_t - K_grid[iK]) / (K_grid[iK + 1] - K_grid[iK])
        pol_at_K = k_opt[:, iK, :] * (1.0 - tK) + k_opt[:, iK + 1, :] * tK   # [ns, nk]
        # Gather-free policy evaluation: state selection and bucket selection
        # are one-hot contractions (ops/interp.py state_policy_interp) — TPU
        # gathers of agent-indexed rows were the measured bottleneck, and the
        # one-hot form also shards cleanly along the agent axis.
        k_new = state_policy_interp(k_grid, pol_at_K, s_t, k_pop)
        K_next = jnp.mean(k_new)
        return (k_new, K_next), K_t

    (k_population, K_last), K_head = jax.lax.scan(
        step, (k_population, jnp.mean(k_population)), (z_path[:-1], eps_panel[:-1])
    )
    K_ts = jnp.concatenate([K_head, K_last[None]])
    return K_ts, k_population
