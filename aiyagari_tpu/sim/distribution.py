"""Non-stochastic (Young 2010) distribution machinery: push the cross-sectional
distribution over (income state, asset) gridpoints through the policy with a
two-point lottery, entirely on device.

The reference approximates the stationary wealth distribution by Monte-Carlo —
a 10,000-period single-household time average (Aiyagari_VFI.m:94-129, quirk 8
in SURVEY.md §3.6) — which is noisy (the GE bisection chases simulation error)
and serial in time. The histogram method replaces it with a deterministic
fixed-point iteration whose hot ops are the lottery push-forward over the
asset axis — scatter-free by default via ops/pushforward.py's monotone-
transpose route; the `.at[].add` scatter kept as the "scatter" parity
backend — and one [N,N]@[N,na] matmul per sweep (MXU-resident), converging
to machine precision in hundreds of sweeps with no RNG at all. The
reference has no analogue; this is a capability the framework adds because
the TPU makes it cheap.

Distribution layout: mu[N, na], mu[i, j] = mass of households in income state
i holding assets a_grid[j]; sums to 1.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.diagnostics.faults import forces_fallback, poison_iterate
from aiyagari_tpu.diagnostics.sentinel import (
    sentinel_cond,
    sentinel_init,
    sentinel_stage_reset,
    sentinel_update,
)
from aiyagari_tpu.diagnostics.telemetry import (
    telemetry_add_fallbacks,
    telemetry_init,
    telemetry_record,
    telemetry_set_trips,
)
from aiyagari_tpu.ops.accel import accel_init, accel_step, project_simplex
from aiyagari_tpu.ops.implicit import fixed_point_vjp
from aiyagari_tpu.ops.interp import bucket_index
from aiyagari_tpu.ops.precision import matmul_precision_of, plan_stages
from aiyagari_tpu.ops.pushforward import (
    apply_pushforward,
    plan_pushforward,
    pushforward_step,
)
from aiyagari_tpu.solvers._stopping import effective_tolerance

__all__ = [
    "DistributionSolution",
    "young_lottery",
    "distribution_step",
    "expectation_step",
    "stationary_distribution",
    "stationary_distribution_implicit",
    "aggregate_capital",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DistributionSolution:
    """Converged cross-sectional distribution mu[N, na] plus iteration telemetry."""

    mu: jax.Array           # [N, na], nonnegative, sums to 1
    iterations: jax.Array   # scalar int32
    distance: jax.Array     # scalar, final sup-norm of the update
    # Mixed-precision ladder telemetry (ops/precision.py; 0 when no ladder
    # ran): sweeps executed in the hot (pre-polish) stages and the residual
    # at which the dtype switch fired (cf. EGMSolution).
    hot_iterations: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0, jnp.int32))
    switch_distance: jax.Array = dataclasses.field(
        default_factory=lambda: jnp.array(0.0))
    # Device-resident flight record (diagnostics/telemetry.py): per-sweep
    # residuals + stage dtypes + accel trips + push-forward fallback sweeps
    # when `telemetry` is set; None when the recorder was compiled out.
    telemetry: object = None
    # Failure-sentinel state (diagnostics/sentinel.py) with the structured
    # early-exit verdict, when `sentinel` is set; None when compiled out.
    sentinel: object = None


# Loud diagnosis of degenerate lottery brackets (duplicate adjacent grid
# knots): opt-in via env var because the check prints from inside traced
# hot loops — the CLAMP below is always on either way.
_LOTTERY_DEBUG = bool(os.environ.get("AIYAGARI_DEBUG_LOTTERY", ""))


def young_lottery(policy_k, a_grid):
    """Split each continuous policy value a' = policy_k[i, j] between its
    bracketing gridpoints (Young 2010's lottery): returns (idx, w_lo) with
    a' = w_lo * a_grid[idx] + (1 - w_lo) * a_grid[idx + 1], w_lo in [0, 1].

    Policies at or beyond the grid edges collapse onto the edge point
    (w_lo clipped), so no mass ever leaves the grid.

    Zero-width brackets: a grid with duplicate/degenerate adjacent knots
    makes (hi - policy_k) / (hi - lo) a 0/0 — NaN mass that would poison
    every downstream sweep silently. The denominator is clamped and the
    bracket's whole mass collapses onto the (single) knot value, which is
    exact: both endpoints ARE the policy value there. Set
    AIYAGARI_DEBUG_LOTTERY=1 to also emit a runtime jax.debug.print when
    any degenerate bracket is hit (regression: tests/test_pushforward.py).
    """
    idx = bucket_index(a_grid, policy_k)
    lo = a_grid[idx]
    hi = a_grid[idx + 1]
    span = hi - lo
    degenerate = span <= 0.0
    w_lo = jnp.clip((hi - policy_k) / jnp.where(degenerate, 1.0, span),
                    0.0, 1.0)
    w_lo = jnp.where(degenerate, 1.0, w_lo)
    if _LOTTERY_DEBUG:
        jax.lax.cond(
            jnp.any(degenerate),
            lambda: jax.debug.print(
                "young_lottery: degenerate zero-width bracket(s) hit — the "
                "asset grid has duplicate adjacent knots; mass collapsed "
                "onto the duplicated point"),
            lambda: None)
    return idx, w_lo


def distribution_step(mu, idx, w_lo, P, precision=jax.lax.Precision.HIGHEST,
                      backend: str = "auto"):
    """One forward iteration of the distribution: move asset mass through the
    policy lottery, then mix income states through P' (one matmul).

    mu'[m, l] = sum_{i,j} P[i, m] * mu[i, j] * lottery(j -> l)

    `backend` selects the push-forward formulation (ops/pushforward.py):
    "auto" (default) runs the scatter-free monotone-transpose route — the
    lottery's scatter buckets are contiguous source segments for a monotone
    policy, computed with two cumsums and a gather at the bucket bounds —
    with a compiled-in fallback to the "scatter" reference when the policy
    is not monotone; "banded"/"pallas" are the MXU/fused alternatives.
    Every backend evaluates the SAME linear operator (summation order is
    the only difference), so parity against "scatter" holds to float
    roundoff and the expectation_step adjoint pairing below is preserved
    for all of them.

    HIGHEST precision by default: the bf16 default would leak mass at ~1e-3.
    The mixed-precision ladder's HOT stages (ops/precision.py) may relax
    `precision` — they renormalize every sweep and their residual target
    sits far above the leak, while the f64 POLISH stage always keeps
    HIGHEST, so the certified mass-conservation contract is unchanged.
    """
    return pushforward_step(mu, idx, w_lo, P, backend=backend,
                            precision=precision)


def expectation_step(f, idx, w_lo, P):
    """Adjoint of distribution_step: pull a state function f[N, na] back one
    period through the same policy lottery and income mixing,

        (L' f)[i, j] = sum_m P[i, m] * ( w_lo[i,j] * f[m, idx[i,j]]
                                       + (1-w_lo[i,j]) * f[m, idx[i,j]+1] ),

    so <f, distribution_step(mu)> == <expectation_step(f), mu> exactly. This
    is the expectation-function recursion of the sequence-space fake-news
    algorithm (Auclert et al. 2021, transition/jacobian.py): iterating it
    from f = policy gives E[policy k periods ahead | state today] under the
    stationary dynamics — one gather + one matmul per period, the forward
    pass's whole cost.

    This gather form is ALREADY scatter-free and stays the single adjoint
    implementation for every DistributionBackend: all backends evaluate
    the same operator L, so the pairing holds against each of them to
    float roundoff (pinned per backend by tests/test_pushforward.py).
    """
    g = jnp.matmul(P, f, precision=jax.lax.Precision.HIGHEST)   # [N, na]
    rows = jnp.broadcast_to(jnp.arange(f.shape[0])[:, None], idx.shape)
    return w_lo * g[rows, idx] + (1.0 - w_lo) * g[rows, idx + 1]


@partial(jax.jit, static_argnames=("noise_floor_ulp", "accel", "ladder",
                                   "pushforward", "telemetry", "sentinel",
                                   "faults"))
def stationary_distribution(policy_k, a_grid, P, *, tol=1e-10,
                            max_iter=10_000, mu_init=None,
                            noise_floor_ulp: float = 0.0,
                            accel=None, ladder=None,
                            pushforward: str = "auto",
                            telemetry=None, sentinel=None,
                            faults=None) -> DistributionSolution:
    """Iterate distribution_step to a sup-norm fixed point on device.

    The whole loop is one lax.while_loop program; the host sees only the
    converged mu. Mass is renormalized each sweep so accumulation error in
    low precision cannot drift the total. mu_init defaults to uniform.

    tol and max_iter are TRACED operands of the while_loop cond — a
    tolerance or iteration-cap sweep reuses the one compiled program
    instead of recompiling it per value (they used to be jit static args).
    The stopping rule routes through the shared
    solvers/_stopping.effective_tolerance, so the distribution loop and the
    household solvers cannot drift apart in convergence semantics
    (noise_floor_ulp = 0 keeps the strict criterion; the floor is exposed
    for fine-grid f32 users exactly as in solvers/egm.solve_aiyagari_egm).

    accel (an AccelConfig, static) opts into safeguarded Anderson/SQUAREM
    acceleration of the power iteration (ops/accel.py). Extrapolated
    iterates re-project onto the simplex (clip negatives, renormalize), so
    every iterate the loop carries IS a distribution; the returned mu is
    always the plain image of the final sweep, satisfying the same
    fixed-point certificate as the unaccelerated solve. Measured ~5x fewer
    sweeps at the reference calibration's tol 1e-10.

    ladder (a PrecisionLadderConfig, static) opts into the mixed-precision
    solve ladder (ops/precision.py): the hot stages power-iterate in the
    narrow dtype (lottery weights, P, and the carry all cast; the
    push-forward matmul at the stage's configured precision) until the
    residual reaches max(tol, switch_ulp * eps * max|mu|), then the carry
    casts up ONCE, re-normalizes on the simplex (the cast must not carry a
    hot-dtype mass defect into the certified stage), the acceleration
    history restarts, and the f64 polish — with the HIGHEST-precision
    mass-conservation matmul this solver always had — runs to the reference
    tolerance. Mass error after the polish stays at f64 roundoff
    (< 1e-12; pinned by tests/test_precision_ladder.py).

    pushforward (a DistributionBackend name, static) selects the sweep's
    push-forward formulation (ops/pushforward.py; default "auto" = the
    scatter-free monotone-transpose route with a compiled-in scatter
    fallback). The per-policy plan — segment bounds, or the banded route's
    block-band operator — is built ONCE per ladder stage and reused by
    every sweep of that stage's while_loop, which is where the scatter-free
    routes earn their keep: thousands of applications of one lottery.

    telemetry (a TelemetryConfig, static) carries a device-resident flight
    recorder through the loop (diagnostics/telemetry.py): per-sweep
    residuals and stage dtypes in a fixed-length ring, accel safeguard
    trips, and — when the plan's scatter-free route is invalid for this
    policy — one push-forward fallback count per degraded sweep, all
    returned as DistributionSolution.telemetry. None compiles the recorder
    out entirely.

    sentinel (a SentinelConfig, static) carries the failure sentinel
    (diagnostics/sentinel.py): non-finite residuals, stalls, and
    explosions early-exit the loop with a structured verdict on
    DistributionSolution.sentinel — the stall watch matters most HERE,
    where max_iter is 10k and an unreachable tolerance otherwise burns all
    of it at the noise floor. faults (a FaultPlan, static) compiles in the
    deterministic injection points (NaN at sweep k; forced push-forward
    fallback). Both default None and compile out entirely.
    """
    N, na = policy_k.shape
    if mu_init is None:
        mu0 = jnp.full((N, na), 1.0 / (N * na), policy_k.dtype)
    else:
        mu0 = mu_init / jnp.sum(mu_init)
    idx, w_lo = young_lottery(policy_k, a_grid)
    max_it = jnp.asarray(max_iter, jnp.int32)
    stages = plan_stages(ladder, mu0.dtype, noise_floor_ulp)

    def run_stage(spec, mu_in, it0, tele_in, sent_in):
        dt = jnp.dtype(spec.dtype)
        # "highest" for final/no-ladder stages (the historical pinned
        # precision); a hot stage's configured relaxation otherwise.
        prec = matmul_precision_of(spec.matmul_precision)
        # Simplex re-normalization AT the cast: a narrow-dtype mass defect
        # must not enter the wider stage as bias.
        mu = mu_in.astype(dt)
        mu = mu / jnp.sum(mu)
        w_lo_d, P_d = w_lo.astype(dt), P.astype(dt)
        # Per-stage plan (the band/bounds cast with the stage dtype),
        # hoisted out of the while_loop: one lottery, thousands of sweeps.
        plan = plan_pushforward(idx, w_lo_d, backend=pushforward)
        if forces_fallback(faults) and plan.ok is not None:
            # Injected degradation (diagnostics/faults.py): the plan's
            # validity flag is forced false, so every sweep takes the
            # compiled-in scatter fallback and tallies a degradation —
            # the CI battery's way of exercising the fallback-counting
            # path on a healthy policy.
            plan = dataclasses.replace(plan, ok=jnp.zeros_like(plan.ok))
        tol_c = jnp.asarray(tol, dt)
        ast0 = accel_init(mu, accel) if accel is not None else None
        trip0 = (tele_in.accel_trips
                 if (tele_in is not None and accel is not None) else None)
        # Per-stage sentinel reference restart: a hot stage exits AT its
        # noise floor, and its `best` would falsely stall the f64 polish
        # (sentinel_stage_reset docstring).
        sent_in = sentinel_stage_reset(sent_in)
        # Degraded-sweep tally: the plan is hoisted, so an invalid
        # scatter-free route (plan.ok False) degrades EVERY sweep of this
        # stage — one fallback event per sweep keeps the count honest.
        fb_per_sweep = (jnp.where(plan.ok, 0, 1).astype(jnp.int32)
                        if (tele_in is not None and plan.ok is not None)
                        else None)

        def cond(carry):
            _, _, dist, it, tol_eff, _, _, sent = carry
            return sentinel_cond(sent, (dist >= tol_eff) & (it < max_it))

        def body(carry):
            mu, _, _, it, _, ast, tele, sent = carry
            mu_new = apply_pushforward(plan, mu, P_d, precision=prec)
            mu_new = mu_new / jnp.sum(mu_new)
            mu_new = poison_iterate(faults, mu_new, it)
            dist = jnp.max(jnp.abs(mu_new - mu))
            tol_eff = effective_tolerance(
                tol_c, jnp.max(jnp.abs(mu_new)),
                noise_floor_ulp=spec.noise_floor_ulp,
                relative_tol=False, dtype=dt)
            tele = telemetry_record(tele, dist)
            sent = sentinel_update(sent, dist, config=sentinel)
            if fb_per_sweep is not None:
                tele = telemetry_add_fallbacks(tele, fb_per_sweep)
            if accel is None:
                mu_next = mu_new
            else:
                mu_next, ast = accel_step(ast, mu, mu_new, accel=accel,
                                          project=project_simplex)
                if trip0 is not None:
                    tele = telemetry_set_trips(tele, trip0 + ast.trips)
            return mu_next, mu_new, dist, it + 1, tol_eff, ast, tele, sent

        _, mu, dist, it, _, _, tele, sent = jax.lax.while_loop(
            cond, body,
            (mu, mu, jnp.array(jnp.inf, dt), it0, tol_c, ast0, tele_in,
             sent_in)
        )
        return mu, dist, it, tele, sent

    mu, it = mu0, jnp.int32(0)
    hot_it = jnp.int32(0)
    switch_dist = jnp.array(0.0, jnp.dtype(stages[-1].dtype))
    tele = telemetry_init(telemetry)
    sent = sentinel_init(sentinel)
    dist = None
    for spec in stages:
        mu, dist, it, tele, sent = run_stage(spec, mu, it, tele, sent)
        if not spec.is_final:
            hot_it = it
            switch_dist = dist.astype(switch_dist.dtype)
    return DistributionSolution(mu, it, dist, hot_it, switch_dist,
                                telemetry=tele, sentinel=sent)


def aggregate_capital(mu, a_grid):
    """E[a] under mu — the capital-supply aggregate, replacing the reference's
    time average mean(sim_k) (Aiyagari_VFI.m:129)."""
    return jnp.sum(mu * a_grid[None, :])


def stationary_distribution_implicit(policy_k, a_grid, P, *, tol=1e-12,
                                     max_iter=10_000, mu_init=None,
                                     pushforward: str = "auto",
                                     adjoint_tol: float = 1e-13,
                                     adjoint_max_iter: int = 5000,
                                     ) -> DistributionSolution:
    """Differentiable view of the stationary distribution (ISSUE 17): run
    stationary_distribution with every input under lax.stop_gradient (the
    primal — bit-identical to the unwrapped solve), then wrap the converged
    mu in ops/implicit.fixed_point_vjp.

    The fixed-point operator wrapped here is the NORMALIZED push-forward
    T(mu) = L mu / sum(L mu) — exactly what the solver iterates (it
    renormalizes every sweep). The normalization is load-bearing for the
    adjoint, not cosmetic: the raw linear operator L is a stochastic map
    with eigenvalue 1 at mu*, so the Neumann series for (I - Lᵀ)⁻¹
    diverges; the normalized step's Jacobian at the fixed point is
    A = (I - mu* 1ᵀ) L, which annihilates the unit eigenvector
    (A mu* = 0) and leaves the subdominant spectrum — the same mixing rate
    that makes the primal iteration converge drives the adjoint.

    The vjp of distribution_step IS expectation_step: the lottery
    push-forward and the P-mixing are one linear operator L, and
    <f, L mu> == <Lᵀ f, mu> with expectation_step as Lᵀ (its docstring
    pins the pairing). jax.vjp recovers that adjoint mechanically from
    the differentiable backend below — the identity is asserted, not
    trusted, by tests/test_differentiable.py.

    Route pin: the adjoint's step runs backend="transpose" — scatter-free
    AND carrying full AD rules — regardless of the primal `pushforward`
    route (which may resolve to Pallas, ruleless). Gradients flow to
    policy_k through the lottery weights w_lo (piecewise-linear in the
    policy: a.e.-differentiable) and to P through the mixing matmul;
    `idx` is integer and correctly carries none.
    """
    sg = jax.lax.stop_gradient
    prim = stationary_distribution(
        sg(policy_k), sg(a_grid), sg(P), tol=tol, max_iter=max_iter,
        mu_init=None if mu_init is None else sg(mu_init),
        pushforward=pushforward)
    # a_grid rides in params so the adjoint's closure captures no arrays
    # (a custom_vjp rule must not close over tracers — this wrapper runs
    # inside jit/vmap in calibrate/economy.py), but its gradient is CUT:
    # grid-knot sensitivities through the lottery's bucket assignment are
    # measure-zero ill-defined, and the grid is frozen under calibration.
    params = (policy_k, a_grid, P)

    def step(mu, p):
        pol, ag, Pm = p
        ag = jax.lax.stop_gradient(ag)
        idx, w_lo = young_lottery(pol, ag)
        mu_new = distribution_step(mu, idx, w_lo, Pm, backend="transpose",
                                   precision=jax.lax.Precision.HIGHEST)
        return mu_new / jnp.sum(mu_new)

    mu_d = fixed_point_vjp(step, prim.mu, params, tol=adjoint_tol,
                           max_iter=adjoint_max_iter)
    return dataclasses.replace(prim, mu=mu_d)
