"""Non-stochastic (Young 2010) distribution machinery: push the cross-sectional
distribution over (income state, asset) gridpoints through the policy with a
two-point lottery, entirely on device.

The reference approximates the stationary wealth distribution by Monte-Carlo —
a 10,000-period single-household time average (Aiyagari_VFI.m:94-129, quirk 8
in SURVEY.md §3.6) — which is noisy (the GE bisection chases simulation error)
and serial in time. The histogram method replaces it with a deterministic
fixed-point iteration whose hot ops are a scatter-add over the asset axis and
one [N,N]@[N,na] matmul per sweep (MXU-resident), converging to machine
precision in hundreds of sweeps with no RNG at all. The reference has no
analogue; this is a capability the framework adds because the TPU makes it
cheap.

Distribution layout: mu[N, na], mu[i, j] = mass of households in income state
i holding assets a_grid[j]; sums to 1.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.interp import bucket_index

__all__ = [
    "DistributionSolution",
    "young_lottery",
    "distribution_step",
    "expectation_step",
    "stationary_distribution",
    "aggregate_capital",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DistributionSolution:
    """Converged cross-sectional distribution mu[N, na] plus iteration telemetry."""

    mu: jax.Array           # [N, na], nonnegative, sums to 1
    iterations: jax.Array   # scalar int32
    distance: jax.Array     # scalar, final sup-norm of the update


def young_lottery(policy_k, a_grid):
    """Split each continuous policy value a' = policy_k[i, j] between its
    bracketing gridpoints (Young 2010's lottery): returns (idx, w_lo) with
    a' = w_lo * a_grid[idx] + (1 - w_lo) * a_grid[idx + 1], w_lo in [0, 1].

    Policies at or beyond the grid edges collapse onto the edge point
    (w_lo clipped), so no mass ever leaves the grid.
    """
    idx = bucket_index(a_grid, policy_k)
    lo = a_grid[idx]
    hi = a_grid[idx + 1]
    w_lo = jnp.clip((hi - policy_k) / (hi - lo), 0.0, 1.0)
    return idx, w_lo


def distribution_step(mu, idx, w_lo, P):
    """One forward iteration of the distribution: move asset mass through the
    policy lottery (scatter-add along the asset axis), then mix income states
    through P' (one matmul).

    mu'[m, l] = sum_{i,j} P[i, m] * mu[i, j] * lottery(j -> l)
    """
    rows = jnp.broadcast_to(jnp.arange(mu.shape[0])[:, None], mu.shape)
    mu_a = (
        jnp.zeros_like(mu)
        .at[rows, idx].add(mu * w_lo)
        .at[rows, idx + 1].add(mu * (1.0 - w_lo))
    )
    # HIGHEST precision: the bf16 default would leak mass at ~1e-3
    return jnp.matmul(P.T, mu_a, precision=jax.lax.Precision.HIGHEST)


def expectation_step(f, idx, w_lo, P):
    """Adjoint of distribution_step: pull a state function f[N, na] back one
    period through the same policy lottery and income mixing,

        (L' f)[i, j] = sum_m P[i, m] * ( w_lo[i,j] * f[m, idx[i,j]]
                                       + (1-w_lo[i,j]) * f[m, idx[i,j]+1] ),

    so <f, distribution_step(mu)> == <expectation_step(f), mu> exactly. This
    is the expectation-function recursion of the sequence-space fake-news
    algorithm (Auclert et al. 2021, transition/jacobian.py): iterating it
    from f = policy gives E[policy k periods ahead | state today] under the
    stationary dynamics — one gather + one matmul per period, the forward
    pass's whole cost.
    """
    g = jnp.matmul(P, f, precision=jax.lax.Precision.HIGHEST)   # [N, na]
    rows = jnp.broadcast_to(jnp.arange(f.shape[0])[:, None], idx.shape)
    return w_lo * g[rows, idx] + (1.0 - w_lo) * g[rows, idx + 1]


@partial(jax.jit, static_argnames=("tol", "max_iter"))
def stationary_distribution(policy_k, a_grid, P, *, tol: float = 1e-10,
                            max_iter: int = 10_000,
                            mu_init=None) -> DistributionSolution:
    """Iterate distribution_step to a sup-norm fixed point on device.

    The whole loop is one lax.while_loop program; the host sees only the
    converged mu. Mass is renormalized each sweep so accumulation error in
    low precision cannot drift the total. mu_init defaults to uniform.
    """
    N, na = policy_k.shape
    if mu_init is None:
        mu = jnp.full((N, na), 1.0 / (N * na), policy_k.dtype)
    else:
        mu = mu_init / jnp.sum(mu_init)
    idx, w_lo = young_lottery(policy_k, a_grid)

    def cond(carry):
        _, dist, it = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        mu, _, it = carry
        mu_new = distribution_step(mu, idx, w_lo, P)
        mu_new = mu_new / jnp.sum(mu_new)
        dist = jnp.max(jnp.abs(mu_new - mu))
        return mu_new, dist, it + 1

    mu, dist, it = jax.lax.while_loop(
        cond, body, (mu, jnp.array(jnp.inf, mu.dtype), jnp.int32(0))
    )
    return DistributionSolution(mu, it, dist)


def aggregate_capital(mu, a_grid):
    """E[a] under mu — the capital-supply aggregate, replacing the reference's
    time average mean(sim_k) (Aiyagari_VFI.m:129)."""
    return jnp.sum(mu * a_grid[None, :])
