"""Ergodic panel simulation for the Aiyagari family.

The reference simulates ONE household for 10,000 periods with a scalar Python
loop (Aiyagari_VFI.m:94-129) and aggregates by the time average (ergodicity).
Here the time axis is a lax.scan (inherently sequential) carrying a whole
*cross-section* of agents as a vector — a panel of 1 reproduces the reference;
a panel of n_agents shards across devices for the scaled runs (SURVEY.md §5.7).
PRNG keys are threaded explicitly, unlike the reference's unseeded `rand`.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.interp import state_policy_interp

__all__ = ["PanelSeries", "simulate_panel"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PanelSeries:
    """Simulated series, each [T, n_agents]: wealth k, consumption c, net
    income y, gross income gy, savings sav, labor l, and the income-state
    index z. Recorded formulas follow Aiyagari_VFI.m:119-122 (with the labor
    factor included for endogenous-labor models, i.e. the VFI variant's
    accounting at Aiyagari_Endogenous_Labor_VFI.m:146 — SURVEY.md §3.6 quirk 4
    resolved in favor of the consistent definition)."""

    k: jax.Array
    c: jax.Array
    y: jax.Array
    gy: jax.Array
    sav: jax.Array
    l: jax.Array
    z: jax.Array


@partial(jax.jit, static_argnames=("periods", "n_agents", "delta"))
def simulate_panel(policy_k, policy_c, policy_l, a_grid, s, P, r, w, key, *,
                   periods: int, n_agents: int = 1, delta: float = 0.08) -> PanelSeries:
    """Simulate `n_agents` independent households for `periods` steps.

    policy_* are [N, na] grid policies evaluated by per-agent linear
    interpolation with extrapolation (Aiyagari_VFI.m:113). The Markov draw
    z' ~ P[z, :] uses the inverse-CDF method: z' = #(cumsum(P[z]) < u), the
    vectorized form of find(rand < cumsum(P(z,:)), 1) at :106.

    Initial conditions mirror :101-102: z0 uniform over states, k0 uniform
    over grid points.
    """
    if periods < 1 or n_agents < 1:
        raise ValueError(f"periods and n_agents must be >= 1, got {periods=}, {n_agents=}")
    N, na = policy_k.shape
    cumP = jnp.cumsum(P, axis=1)
    k_init, k_z, k_scan = jax.random.split(key, 3)
    z0 = jax.random.randint(k_z, (n_agents,), 0, N)
    k0 = a_grid[jax.random.randint(k_init, (n_agents,), 0, na)]

    def step(carry, key_t):
        z, k = carry
        u = jax.random.uniform(key_t, (n_agents,), dtype=a_grid.dtype)
        # Markov draw via one-hot row selection (gather-free) + inverse CDF.
        ohZ = (z[:, None] == jnp.arange(N)[None, :]).astype(a_grid.dtype)
        rowP = jnp.matmul(ohZ, cumP, precision=jax.lax.Precision.HIGHEST)
        z_new = jnp.sum(rowP < u[:, None], axis=1).astype(z.dtype)
        k_new = state_policy_interp(a_grid, policy_k, z_new, k)
        c_new = state_policy_interp(a_grid, policy_c, z_new, k)
        l_new = state_policy_interp(a_grid, policy_l, z_new, k)
        ohZn = (z_new[:, None] == jnp.arange(N)[None, :]).astype(a_grid.dtype)
        s_new = jnp.matmul(ohZn, s, precision=jax.lax.Precision.HIGHEST)
        labor_inc = w * s_new * l_new
        y = r * k_new + labor_inc
        gy = y + delta * k_new
        sav = gy - c_new
        return (z_new, k_new), (k_new, c_new, y, gy, sav, l_new, z_new)

    keys = jax.random.split(k_scan, periods)
    _, (k, c, y, gy, sav, l, z) = jax.lax.scan(step, (z0, k0), keys)
    return PanelSeries(k, c, y, gy, sav, l, z)
