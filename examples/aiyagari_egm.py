#!/usr/bin/env python
"""Aiyagari (1994), exogenous labor, endogenous grid method (Carroll 2006).

Framework counterpart of the reference's Aiyagari_EGM.m (EGM operator :74-110,
simulation :120-149, GE bisection :157-253 — with the stale-wage quirk fixed:
both r and w are recomputed each bisection step, SURVEY.md §3.6 quirk 1).

Run: python examples/aiyagari_egm.py [--quick] [--outdir out/] [--progress 50]
"""

import _common

args = _common.example_args(__doc__)

import aiyagari_tpu as at

cfg = at.AiyagariConfig() if not args.quick else at.AiyagariConfig(
    grid=at.GridSpecConfig(n_points=100)
)
sim = at.SimConfig() if not args.quick else at.SimConfig(
    periods=2000, n_agents=8, discard=200, seed=0
)
res = at.solve(
    cfg, method="egm", sim=sim,
    solver=at.SolverConfig(method="egm", progress_every=args.progress),
)
_common.print_equilibrium(res, "Aiyagari / EGM")

if args.outdir:
    from aiyagari_tpu.io_utils.report import equilibrium_report
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    summary = equilibrium_report(res, AiyagariModel.from_config(cfg), args.outdir,
                                 discard=sim.discard)
    print(f"report written to {args.outdir}: {sorted(summary)}")
