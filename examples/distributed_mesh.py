#!/usr/bin/env python
"""Distributed solves on a device mesh — the framework's scaling surface.

No reference counterpart (the reference is six serial MATLAB scripts);
this example demonstrates the round-3-5 distributed machinery on whatever
devices are available. Without real multi-chip hardware it forces a
virtual 8-device CPU mesh (the same topology the test suite and the
driver dryrun validate), so it runs anywhere:

  1. Aiyagari GE bisection with the asset grid SHARDED over the mesh
     (ring-redistributed endogenous knots, solvers/egm_sharded.py),
     checked against the single-device solve.
  2. Krusell-Smith ALM fixed point with the fine capital grid sharded —
     both household methods: EGM (ring slab + masked pchip) and VFI
     (replicated-table / local-candidate program, round 5).
  3. The agent-panel data-parallel route (mean lowers to a psum).

Run: python examples/distributed_mesh.py
(always quick-scaled; the point is the routing, not the wall-clock —
still ~15 min on a one-core box, which is why this script is NOT a suite
smoke: every route it drives is already pinned by test_egm_sharded /
test_ks_sharded / test_sim_sharding and the driver dryrun; this is the
user-facing composition of them.)
"""

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

ap = argparse.ArgumentParser(description=__doc__)
ap.add_argument("--platform", choices=["cpu", "tpu"], default="cpu",
                help="cpu (default): force a virtual 8-device CPU mesh; "
                     "tpu: use the attached TPU devices as the mesh (a "
                     "single-chip attachment has no grid axis to split — "
                     "meant for real multi-chip slices)")
args = ap.parse_args()

if args.platform == "cpu":
    # Force the virtual mesh BEFORE jax initializes (a real TPU pod skips
    # this and uses the actual devices; docs/USAGE.md "Scaling up").
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", args.platform)
if args.platform == "cpu":
    jax.config.update("jax_enable_x64", True)

from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

enable_compilation_cache()

import numpy as np

import aiyagari_tpu as at

print(f"devices: {len(jax.devices())} x {jax.devices()[0].platform}")

# 1. Mesh-routed Aiyagari GE: grid sharding via BackendConfig. 6,144 points
#    is the smallest ring-slab-sound grid at D=8; 2 bisection iterations
#    pin the routing (the full fixed point is the test suite's job).
cfg = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=6_144))
eq = at.EquilibriumConfig(max_iter=2)
ref = at.solve(cfg, method="egm", aggregation="distribution", equilibrium=eq)
res = at.solve(cfg, method="egm", aggregation="distribution", equilibrium=eq,
               backend=at.BackendConfig(mesh_axes=("grid",)))
gap = abs(res.r - ref.r)
print(f"[1] sharded Aiyagari GE: r={res.r:.6f} (single-device gap {gap:.2e})")
assert gap < 1e-10

# 2. Sharded Krusell-Smith, both methods. k_size=128 -> 16 points/device.
ks_kw = dict(alm=at.ALMConfig(T=120, population=400, discard=20, max_iter=2))
for method, solver in (
    ("egm", at.SolverConfig(method="egm", tol=1e-5, max_iter=2000)),
    ("vfi", at.SolverConfig(method="vfi", tol=1e-4, max_iter=30,
                            howard_steps=10)),
):
    ks = at.solve(at.KrusellSmithConfig(k_size=128), method=method,
                  solver=solver,
                  backend=at.BackendConfig(mesh_axes=("grid",)), **ks_kw)
    print(f"[2] sharded K-S / {method}: R^2 = "
          f"{float(ks.r2[0]):.5f}/{float(ks.r2[1]):.5f}, "
          f"B = {np.round(np.asarray(ks.B), 3).tolist()}")

# 3. Agent-parallel panel: the cross-section spans the mesh; K = mean(k)
#    is a psum over the device axis.
ks_dp = at.solve(at.KrusellSmithConfig(k_size=30), method="egm",
                 solver=at.SolverConfig(method="egm", tol=1e-5,
                                        max_iter=2000),
                 backend=at.BackendConfig(mesh_axes=("agents",)),
                 alm=at.ALMConfig(T=120, population=800, discard=20,
                                  max_iter=2))
print(f"[3] agent-parallel K-S: R^2 = {float(ks_dp.r2[0]):.5f}/"
      f"{float(ks_dp.r2[1]):.5f}")
print("distributed_mesh ok")
