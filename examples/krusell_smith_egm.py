#!/usr/bin/env python
"""Krusell-Smith (1998) with aggregate risk, EGM policy iteration.

Framework counterpart of the reference's Krusell_Smith_EGM.m (EGM sweep with
the ALM applied twice per expectation :128-209, panel simulation :227-253,
ALM regression :255-301).

Run: python examples/krusell_smith_egm.py [--quick] [--outdir out/]
"""

import _common

args = _common.example_args(__doc__)

import aiyagari_tpu as at

if args.quick:
    cfg = at.KrusellSmithConfig(k_size=30)
    alm = at.ALMConfig(T=300, population=2000, discard=50, max_iter=10)
    solver = at.SolverConfig(method="egm", tol=1e-5, max_iter=2000,
                             progress_every=args.progress)
else:
    cfg = at.KrusellSmithConfig()
    alm = at.ALMConfig()
    # Reference defaults (tol 1e-6, <=10000 sweeps), with the telemetry
    # cadence threaded through so --progress works here too.
    solver = at.SolverConfig(method="egm", tol=1e-6, max_iter=10_000,
                             progress_every=args.progress)
res = at.solve(cfg, method="egm", solver=solver, alm=alm,
               aggregation=("distribution" if args.closure == "histogram" else "simulation"))
_common.print_ks(res, f"Krusell-Smith / EGM ({args.closure} closure)")

if args.outdir:
    from aiyagari_tpu.io_utils.report import krusell_smith_report

    summary = krusell_smith_report(res, args.outdir, discard=alm.discard)
    print(f"report written to {args.outdir}: {sorted(summary)}")
