#!/usr/bin/env python
"""Scenario sweep: a beta x sigma grid of Aiyagari economies solved to
general equilibrium as ONE batched device program (dispatch.sweep /
equilibrium/batched.py), plus the same economy re-solved with the
parallel-bracket batched root finder (EquilibriumConfig(batch=B)).

No reference-script counterpart: the reference solves one calibration per
run; this is the "as many scenarios as you can imagine" axis the framework
adds. Every bisection round here is a single vmapped excess-demand kernel
over all scenarios (sharded over a "scenarios" mesh axis when the host has
multiple devices).

Run: python examples/sweep_scenarios.py [--quick] [--platform cpu]
"""

import _common

args = _common.example_args(__doc__)

import numpy as np

import aiyagari_tpu as at

n_points = 100 if args.quick else 200
betas = [0.94, 0.96] if args.quick else [0.93, 0.94, 0.95, 0.96]
sigmas = [3.0, 5.0]

base = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=n_points))
eq = at.EquilibriumConfig(max_iter=8 if args.quick else 18, tol=1e-3)

res = at.sweep(base, method="egm", beta=betas, sigma=sigmas, equilibrium=eq)

print(f"sweep: {res.scenarios} scenarios x {n_points}-point grids, "
      f"{res.rounds} lockstep rounds, "
      f"{res.scenarios_per_sec:.2f} scenarios/sec")
for p, r, k, ok in zip(res.params, res.r, res.capital, res.converged):
    tag = "" if ok else "  (hit round cap)"
    print(f"  beta={p['beta']:.2f} sigma={p['sigma']:.1f}: "
          f"r* = {r:.4f}, K = {k:.3f}{tag}")

# Economics sanity the sweep should reproduce: more patience (higher beta)
# or more risk aversion (higher sigma) -> more precautionary saving ->
# lower equilibrium r.
r_grid = np.asarray(res.r).reshape(len(betas), len(sigmas))
assert np.all(np.diff(r_grid, axis=0) < 0), "r* should fall with beta"
assert np.all(np.diff(r_grid, axis=1) < 0), "r* should fall with sigma"

# The same root, found B candidates per round instead of one per iteration.
mid = base
bat = at.solve(mid, method="egm", aggregation="distribution",
               equilibrium=at.EquilibriumConfig(batch=8, max_iter=8, tol=1e-3),
               on_nonconvergence="ignore")
print(f"batched-bracket solve of the base economy: r* = {bat.r:.4f} in "
      f"{bat.iterations} rounds ({'converged' if bat.converged else 'cap'})")
