#!/usr/bin/env python
"""MIT-shock transition dynamics: the perfect-foresight equilibrium path of
an Aiyagari economy hit by a one-time unanticipated TFP shock, solved by
Newton on the price path with the sequence-space Jacobian (transition/;
Boppart-Krusell-Mitman 2018, Auclert et al. 2021 — see PAPERS.md), plus a
lockstep sweep over a grid of shock scenarios.

No reference-script counterpart: the six reference MATLAB scripts solve
stationary problems only. This is the workload the transition subsystem
adds — every Newton round is ONE fused backward-sweep + forward-push device
program, and whole shock scenarios batch over the vmapped twin.

Run: python examples/mit_shock.py [--quick] [--platform cpu]
"""

import _common

args = _common.example_args(__doc__)

import numpy as np

import aiyagari_tpu as at

n_points = 80 if args.quick else 200
T = 60 if args.quick else 200

cfg = at.AiyagariConfig(grid=at.GridSpecConfig(n_points=n_points))
shock = at.MITShock(param="tfp", size=0.01, rho=0.9)
tc = at.TransitionConfig(T=T, tol=1e-7, method="newton", max_iter=20)

res = at.solve_transition(cfg, shock, transition=tc,
                          on_iteration=lambda r: print(
                              f"  round {r['round']}: max excess demand "
                              f"{r['max_excess']:.3e} ({r['seconds']:.2f}s)"))

print(f"== MIT shock: +{100 * shock.size:.0f}% TFP, persistence "
      f"{shock.rho}, T = {T} ==")
print(f"stationary anchor: r* = {res.r_ss:.6f}, K* = {res.K_ss:.4f}")
print(f"newton rounds = {res.rounds}  converged = {res.converged}  "
      f"final max excess = {res.max_excess_history[-1]:.2e}")
t_peak = int(np.argmax(res.K_ts))
dev = np.abs(res.K_ts - res.K_ss)
after = dev[t_peak:] < 0.5 * dev[t_peak]
print(f"impact: r_0 - r* = {res.r_path[0] - res.r_ss:+.5f}, "
      f"peak K = {np.max(res.K_ts):.4f} at t = {t_peak}")
if after.any():
    print(f"half-life of the K deviation past its peak: "
          f"{int(np.argmax(after))} periods")

# The same economy under a grid of shock scenarios — sizes x persistences,
# plus a discount-factor shock — advanced in lockstep through one vmapped
# path program, reusing the stationary anchor and the fake-news Jacobian.
shocks = [at.MITShock("tfp", sz, rh)
          for sz in (0.005, 0.01) for rh in (0.8, 0.9)]
shocks.append(at.MITShock("beta", 0.002, 0.8))
sw = at.sweep_transitions(cfg, shocks, transition=tc,
                          ss=res.ss, jacobian=res.jacobian)
print(f"\n== scenario sweep: {sw.scenarios} shocks, {sw.rounds} lockstep "
      f"rounds, {sw.transitions_per_sec:.2f} transitions/sec ==")
for sh, r0, kpk, ok in zip(shocks, sw.r_paths[:, 0],
                           np.max(sw.K_ts, axis=1), sw.converged):
    tag = "" if ok else "  (hit round cap)"
    print(f"  {sh.param:>15} size={sh.size:+.3f} rho={sh.rho}: "
          f"r_0 = {r0:.5f}, peak K = {kpk:.4f}{tag}")

# Economics the transition should reproduce: a bigger or more persistent
# expansionary TFP shock moves the impact rate and the capital peak more.
r0 = sw.r_paths[:4, 0].reshape(2, 2)
assert np.all(r0[1] > r0[0]), "larger TFP shock should raise the impact rate"
assert sw.converged.all() or args.quick

if args.outdir:
    import json
    from pathlib import Path

    out = Path(args.outdir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "mit_shock_summary.json").write_text(json.dumps({
        "r_ss": res.r_ss, "K_ss": res.K_ss, "rounds": res.rounds,
        "converged": res.converged,
        "max_excess_history": res.max_excess_history,
        "r_path": res.r_path.tolist(), "K_ts": res.K_ts.tolist(),
        "sweep_transitions_per_sec": sw.transitions_per_sec,
    }, indent=2))
    print(f"\nwrote {out / 'mit_shock_summary.json'}")
