#!/usr/bin/env python
"""Aiyagari (1994), exogenous labor, value-function iteration.

Framework counterpart of the reference's Aiyagari_VFI.m (all 420 lines:
Tauchen discretization :18-35, 400-point quadratic asset grid :51-58, VFI
household solve :65-90, ergodic simulation :94-129, GE bisection on r
:133-206, distributional statistics and plots :215-420).

Run: python examples/aiyagari_vfi.py [--quick] [--outdir out/] [--progress 50]
"""

import _common

args = _common.example_args(__doc__)

import aiyagari_tpu as at

cfg = at.AiyagariConfig() if not args.quick else at.AiyagariConfig(
    grid=at.GridSpecConfig(n_points=100)
)
sim = at.SimConfig() if not args.quick else at.SimConfig(
    periods=2000, n_agents=8, discard=200, seed=0
)
res = at.solve(
    cfg, method="vfi", sim=sim,
    solver=at.SolverConfig(method="vfi", progress_every=args.progress),
)
_common.print_equilibrium(res, "Aiyagari / VFI")

if args.outdir:
    from aiyagari_tpu.io_utils.report import equilibrium_report
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    summary = equilibrium_report(res, AiyagariModel.from_config(cfg), args.outdir,
                                 discard=sim.discard)
    print(f"report written to {args.outdir}: {sorted(summary)}")
