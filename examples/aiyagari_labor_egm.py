#!/usr/bin/env python
"""Aiyagari with endogenous labor supply, EGM with the intratemporal FOC.

Framework counterpart of the reference's Aiyagari_Endogenous_Labor_EGM.m
(closed-form labor FOC l = ((w s u'(c))/psi)^(1/eta) :61-62,86, EGM operator
:67-107, GE bisection :154-255).

Run: python examples/aiyagari_labor_egm.py [--quick] [--outdir out/]
"""

import _common

args = _common.example_args(__doc__)

import aiyagari_tpu as at

grid = at.GridSpecConfig(n_points=100) if args.quick else at.GridSpecConfig()
cfg = at.AiyagariConfig(endogenous_labor=True, grid=grid)
sim = at.SimConfig() if not args.quick else at.SimConfig(
    periods=2000, n_agents=8, discard=200, seed=0
)
res = at.solve(
    cfg, method="egm", sim=sim,
    solver=at.SolverConfig(method="egm", progress_every=args.progress),
)
_common.print_equilibrium(res, "Aiyagari endogenous labor / EGM")
import jax.numpy as jnp

print(f"mean labor supply = {float(jnp.mean(res.series.l)):.4f}")

if args.outdir:
    from aiyagari_tpu.io_utils.report import equilibrium_report
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    summary = equilibrium_report(res, AiyagariModel.from_config(cfg), args.outdir,
                                 discard=sim.discard)
    print(f"report written to {args.outdir}: {sorted(summary)}")
