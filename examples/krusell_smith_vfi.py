#!/usr/bin/env python
"""Krusell-Smith (1998) with aggregate risk, Howard-accelerated VFI.

Framework counterpart of the reference's Krusell_Smith_VFI.m (duration-based
4-state chain :23-55, shock panel :58-94, Howard VFI with continuous policy
improvement :141-204, 10,000-agent panel simulation :222-248, ALM regression
with damped update :250-296, diagnostics :298-325).

Run: python examples/krusell_smith_vfi.py [--quick] [--outdir out/]
"""

import _common

args = _common.example_args(__doc__)

import aiyagari_tpu as at

if args.quick:
    cfg = at.KrusellSmithConfig(k_size=30)
    alm = at.ALMConfig(T=300, population=2000, discard=50, max_iter=10)
    solver = at.SolverConfig(method="vfi", tol=1e-5, max_iter=200,
                             howard_steps=20, progress_every=args.progress)
else:
    cfg = at.KrusellSmithConfig()
    alm = at.ALMConfig()
    # Reference defaults (tol 1e-6, Howard 50, improve every 5), with the
    # telemetry cadence threaded through so --progress works here too.
    solver = at.SolverConfig(method="vfi", tol=1e-6, max_iter=10_000,
                             howard_steps=50, improve_every=5, relative_tol=True,
                             progress_every=args.progress)
res = at.solve(cfg, method="vfi", solver=solver, alm=alm,
               aggregation=("distribution" if args.closure == "histogram" else "simulation"))
_common.print_ks(res, f"Krusell-Smith / Howard VFI ({args.closure} closure)")

if args.outdir:
    from aiyagari_tpu.io_utils.report import krusell_smith_report

    summary = krusell_smith_report(res, args.outdir, discard=alm.discard)
    print(f"report written to {args.outdir}: {sorted(summary)}")
