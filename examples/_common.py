"""Shared CLI plumbing for the example scripts.

Every example mirrors one of the six reference scripts end-to-end
(parameterize -> discretize -> solve -> simulate -> close GE -> report) at the
reference's scale by default; --quick shrinks grids/horizons for a fast smoke
run, --outdir writes the full plot/statistics report, --platform forces the
jax backend (the JAX_PLATFORMS env var alone does not stick in images whose
TPU plugin registers at interpreter startup).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

# Make the repo root importable so the examples run without installation.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def example_args(description: str) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--quick", action="store_true", help="small grids/horizons smoke run")
    ap.add_argument("--outdir", default=None, help="write the plot/stats report here")
    ap.add_argument("--platform", choices=["cpu", "tpu"], default=None)
    ap.add_argument("--progress", type=int, default=0, metavar="N",
                    help="emit in-jit solver telemetry every N sweeps")
    ap.add_argument("--closure", choices=["panel", "histogram"], default="panel",
                    help="Krusell-Smith cross-section: Monte-Carlo agent panel "
                         "(reference-faithful) or deterministic Young histogram "
                         "(no sampling noise; K-S examples only)")
    args = ap.parse_args()

    import jax

    if args.platform:
        # Pass the platform through verbatim so --platform tpu errors loudly
        # if the TPU backend is unavailable instead of silently running CPU.
        jax.config.update("jax_platforms", args.platform)
    # After the platform choice: the cache dir is keyed by it
    # (io_utils/compile_cache.py).
    from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    if jax.default_backend() != "tpu":
        jax.config.update("jax_enable_x64", True)
    if args.progress:
        from aiyagari_tpu.diagnostics import ConsoleSink, subscribe

        subscribe(ConsoleSink(prefix="  [solver] "))
    return args


def print_equilibrium(res, label: str) -> None:
    from aiyagari_tpu.utils.stats import gini, quantile_shares

    print(f"== {label} ==")
    print(f"r* = {res.r:.6f}   w = {res.w:.6f}   K = {float(res.capital):.4f}   "
          f"iterations = {len(res.r_history)}  converged = {res.converged}")
    k = res.series.k if hasattr(res, "series") else res.sim_k
    g = float(gini(k.reshape(-1)))
    shares = [round(float(x), 4) for x in quantile_shares(k.reshape(-1))]
    print(f"wealth gini = {g:.4f}   quintile shares = {shares}")


def print_ks(res, label: str) -> None:
    print(f"== {label} ==")
    print(f"B = {[round(float(b), 5) for b in res.B]}")
    print(f"per-regime R^2 = {[round(float(x), 5) for x in res.r2]}   "
          f"iterations = {res.iterations}  converged = {res.converged}")
